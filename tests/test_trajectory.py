"""Bench-trajectory sentinel (ISSUE 12): artifact ingestion across the
three artifact shapes, provenance tagging, noise-aware regression
detection, and the CLI contract tools/lint.sh relies on (clean skip on an
artifact-less checkout, nonzero exit on a regression)."""

import json
import os

from coreth_tpu.bench.trajectory import (OUTPUT, build_trajectory,
                                         load_artifacts, main)


def _suite(tmp_path, rnd, value, platform="cpu-backend (tunnel wedged)",
           config=3, metric="block_insert_1k_txs_per_sec", unit="txs/s",
           extra=None):
    results = [{"config": config, "metric": metric, "value": value,
                "unit": unit, "vs_baseline": 1.0}]
    if extra:
        results += extra
    (tmp_path / f"BENCH_SUITE_r{rnd:02d}.json").write_text(json.dumps(
        {"round": rnd, "platform": platform, "results": results}))


def _series(out):
    return out["series"]


class TestIngestion:
    def test_three_artifact_shapes_normalize(self, tmp_path):
        _suite(tmp_path, 1, 1000.0)
        (tmp_path / "BENCH_r01.json").write_text(json.dumps({
            "n": 1, "cmd": "python bench.py", "rc": 0, "tail": "",
            "parsed": {"metric": "trie_commit_nodes_per_sec",
                       "value": 32000.0, "unit": "nodes/s",
                       "vs_baseline": 0.4}}))
        (tmp_path / "BENCH_EARLY_r01.json").write_text(json.dumps({
            "metric": "trie_commit_nodes_per_sec", "value": 59000.0,
            "unit": "nodes/s", "platform": "TPU v5 lite (axon tunnel, live)",
            "mode": "early"}))
        points, skipped = load_artifacts(str(tmp_path))
        assert len(points) == 3 and skipped == []
        out = build_trajectory(points, skipped)
        assert set(_series(out)) == {
            "cfg=3|block_insert_1k_txs_per_sec|xla-cpu-standin",
            "cfg=device-leg|trie_commit_nodes_per_sec|real-device",
            "cfg=early|trie_commit_nodes_per_sec|real-device",
        }

    def test_provenance_tags(self, tmp_path):
        # host_mode flag (even from a metric-less companion dict) beats
        # the platform string; "live" platforms are real-device
        _suite(tmp_path, 1, 200.0, platform="TPU v5 (live)", config=10,
               metric="resident_block_insert_txs_per_sec",
               extra=[{"config": 10, "host_mode": True,
                       "cold_txs_per_sec": 190.0}])
        points, _ = load_artifacts(str(tmp_path))
        assert points[0]["provenance"] == "host_mode"

    def test_config19_shard_sweep_ingests_with_honest_provenance(
            self, tmp_path):
        # the exec-shard A/B is a CPU-process bench: its companion line
        # stamps host_mode + cores, so the series is tagged host_mode and
        # the noise gate never mistakes a 1-core ~1.0x round for a
        # device-leg regression
        for rnd in (1, 2, 3):
            _suite(tmp_path, rnd, 1200.0 + rnd, config=19,
                   metric="sharded_block_insert_txs_per_sec",
                   extra=[{"config": 19, "host_mode": True, "cores": 1,
                           "serial_txs_per_sec": 1100.0,
                           "shards": {"4": {"ratio_vs_serial": 1.01}}}])
        points, skipped = load_artifacts(str(tmp_path))
        cfg19 = [p for p in points if p["config"] == 19]
        assert len(cfg19) == 3 and skipped == []
        assert all(p["provenance"] == "host_mode" for p in cfg19)
        out = build_trajectory(points, skipped)
        key = "cfg=19|sharded_block_insert_txs_per_sec|host_mode"
        assert out["series"][key]["n"] == 3

    def test_unmeasured_device_leg_is_skipped_not_a_point(self, tmp_path):
        (tmp_path / "BENCH_r02.json").write_text(json.dumps({
            "n": 2, "cmd": "python bench.py", "rc": 0, "tail": "",
            "parsed": {"metric": "trie_commit_nodes_per_sec", "value": 0.0,
                       "unit": "nodes/s", "vs_baseline": 0.0,
                       "error": "device wedged: tunnel hang"}}))
        points, skipped = load_artifacts(str(tmp_path))
        assert points == []
        assert len(skipped) == 1
        assert "wedged" in skipped[0]["reason"]

    def test_own_output_out_of_scope(self, tmp_path):
        (tmp_path / OUTPUT).write_text('{"schema": "stale"}')
        points, skipped = load_artifacts(str(tmp_path))
        assert points == [] and skipped == []

    def test_multichip_ok_round_parses_coverage_series(self, tmp_path):
        # the r04+ tail shape: checksum sweep + sharded planned commit
        # (new "— N nodes" wording) + resident churn line
        (tmp_path / "MULTICHIP_r04.json").write_text(json.dumps({
            "round": 4, "ok": True, "rc": 0, "n_devices": 8,
            "tail": "dryrun_multichip OK: 1024 lanes over 8 devices\n"
                    "sharded planned commit — 26862 nodes, 17 segments\n"
                    "RESIDENT executor sharded over 8 devices — 3 churn "
                    "rounds + rollback bit-exact vs host oracle"}))
        points, skipped = load_artifacts(str(tmp_path))
        assert skipped == []
        got = {p["metric"]: p["value"] for p in points}
        assert got == {"multichip_checksum_lanes": 1024.0,
                       "multichip_planned_nodes": 26862.0,
                       "multichip_planned_segments": 17.0,
                       "multichip_resident_churn_rounds": 3.0}
        assert all(p["provenance"] == "xla-cpu-standin" for p in points)
        assert all(p["config"] == "multichip-8dev" for p in points)
        # counts have no judgeable direction: reported, never gated
        out = build_trajectory(points, [])
        assert out["regressions"] == []
        for s in out["series"].values():
            assert s["status"] in ("short", "unjudged")

    def test_multichip_old_tail_format_still_parses(self, tmp_path):
        # the r02-era wording ("commit of N nodes")
        (tmp_path / "MULTICHIP_r02.json").write_text(json.dumps({
            "round": 2, "ok": True, "rc": 0, "n_devices": 8,
            "tail": "sharded planned commit of 412 nodes matches the "
                    "host oracle root"}))
        points, _ = load_artifacts(str(tmp_path))
        assert {p["metric"] for p in points} == {"multichip_planned_nodes"}
        assert points[0]["value"] == 412.0

    def test_multichip_wedged_round_is_skipped_not_a_point(self, tmp_path):
        (tmp_path / "MULTICHIP_r01.json").write_text(json.dumps({
            "round": 1, "ok": False, "rc": 124, "n_devices": 8,
            "tail": ""}))
        points, skipped = load_artifacts(str(tmp_path))
        assert points == []
        assert len(skipped) == 1
        assert skipped[0]["reason"] == "dryrun wedged (rc=124)"

    def test_multichip_pallas_dumps_stay_out_of_scope(self, tmp_path):
        # numeric-parity dumps share the prefix but aren't dryrun rounds
        (tmp_path / "MULTICHIP_PALLAS_r03.json").write_text('{"raw": 1}')
        points, skipped = load_artifacts(str(tmp_path))
        assert points == [] and skipped == []


def _storm(tmp_path, rnd, view_sat=3400.0, locked_sat=2900.0,
           view_p99=300.0, smoke=False):
    legs = {
        "locked": {"saturation_per_sec": locked_sat,
                   "methods": {"eth_getBalance": {
                       "count": 100, "p50_ms": 280.0, "p90_ms": 530.0,
                       "p99_ms": 570.0}}},
        "view": {"saturation_per_sec": view_sat,
                 "methods": {"eth_getBalance": {
                     "count": 100, "p50_ms": 240.0, "p90_ms": 290.0,
                     "p99_ms": view_p99}}},
    }
    (tmp_path / f"BENCH_STORM_r{rnd:02d}.json").write_text(json.dumps({
        "schema": "bench-storm/v1", "config": 18, "platform": "cpu",
        "host_mode": True, "smoke": smoke, "legs": legs,
        "view_vs_locked_saturation": round(view_sat / locked_sat, 3)}))


class TestStormIngestion:
    def test_storm_artifact_yields_per_leg_series(self, tmp_path):
        _storm(tmp_path, 13)
        points, skipped = load_artifacts(str(tmp_path))
        assert skipped == []
        by_metric = {p["metric"]: p for p in points}
        assert set(by_metric) == {
            "storm_locked_saturation_per_sec",
            "storm_locked_eth_getBalance_p99_ms",
            "storm_view_saturation_per_sec",
            "storm_view_eth_getBalance_p99_ms",
        }
        # a host-concurrency bench: no device code ran
        assert all(p["provenance"] == "host_mode" for p in points)
        assert all(p["config"] == 18 for p in points)
        assert by_metric["storm_view_saturation_per_sec"][
            "vs_baseline"] == 1.172
        out = build_trajectory(points, skipped)
        sat = out["series"]["cfg=18|storm_view_saturation_per_sec|host_mode"]
        p99 = out["series"][
            "cfg=18|storm_view_eth_getBalance_p99_ms|host_mode"]
        assert sat["direction"] == "higher"   # goodput: more is better
        assert p99["direction"] == "lower"    # tail latency: less is better

    def test_smoke_storm_is_skipped_not_a_point(self, tmp_path):
        _storm(tmp_path, 14, smoke=True)
        points, skipped = load_artifacts(str(tmp_path))
        assert points == []
        assert len(skipped) == 1
        assert "smoke" in skipped[0]["reason"]

    def test_p99_blowup_fails_check(self, tmp_path):
        # noise-aware gate on the storm series: p99 is lower-is-better,
        # so a 2x tail-latency blowup in the newest round must trip it
        for rnd, p99 in ((1, 300.0), (2, 310.0), (3, 295.0), (4, 640.0)):
            _storm(tmp_path, rnd, view_p99=p99)
        assert main(["--check", "--root", str(tmp_path)]) == 1
        out = json.loads((tmp_path / OUTPUT).read_text())
        assert any("storm_view_eth_getBalance_p99_ms" in r["series"]
                   for r in out["regressions"])

    def test_saturation_collapse_fails_check(self, tmp_path):
        for rnd, sat in ((1, 3400.0), (2, 3450.0), (3, 3380.0), (4, 2100.0)):
            _storm(tmp_path, rnd, view_sat=sat)
        assert main(["--check", "--root", str(tmp_path)]) == 1

    def test_stable_storm_rounds_pass(self, tmp_path):
        for rnd, sat in ((1, 3400.0), (2, 3450.0), (3, 3380.0), (4, 3420.0)):
            _storm(tmp_path, rnd, view_sat=sat)
        assert main(["--check", "--root", str(tmp_path)]) == 0


class TestRegressionGate:
    def test_twenty_percent_regression_fails_check(self, tmp_path, capsys):
        for rnd, v in ((1, 1000.0), (2, 1010.0), (3, 995.0), (4, 800.0)):
            _suite(tmp_path, rnd, v)
        rc = main(["--check", "--root", str(tmp_path)])
        assert rc == 1
        assert "REGRESSION" in capsys.readouterr().out
        out = json.loads((tmp_path / OUTPUT).read_text())
        assert len(out["regressions"]) == 1
        key = out["regressions"][0]["series"]
        assert out["series"][key]["status"] == "regression"

    def test_stable_series_passes(self, tmp_path):
        for rnd, v in ((1, 1000.0), (2, 1010.0), (3, 995.0), (4, 1005.0)):
            _suite(tmp_path, rnd, v)
        assert main(["--check", "--root", str(tmp_path)]) == 0

    def test_in_band_dip_is_not_a_regression(self, tmp_path):
        # 8% down is inside the 10% relative floor
        for rnd, v in ((1, 1000.0), (2, 1010.0), (3, 995.0), (4, 920.0)):
            _suite(tmp_path, rnd, v)
        assert main(["--check", "--root", str(tmp_path)]) == 0

    def test_noisy_series_never_gates(self, tmp_path):
        # tunnel-era swings: relative MAD way past 0.5 -> reported, not gated
        for rnd, v in ((1, 100.0), (2, 1700.0), (3, 300.0), (4, 40.0)):
            _suite(tmp_path, rnd, v)
        assert main(["--check", "--root", str(tmp_path)]) == 0
        out = json.loads((tmp_path / OUTPUT).read_text())
        assert list(out["series"].values())[0]["status"] == "noisy"

    def test_lower_is_better_direction(self, tmp_path):
        for rnd, v in ((1, 1.0), (2, 1.02), (3, 0.99), (4, 1.5)):
            _suite(tmp_path, rnd, v, metric="chain_insert_latency_s",
                   unit="s")
        rc = main(["--check", "--root", str(tmp_path)])
        assert rc == 1

    def test_short_series_unchecked(self, tmp_path):
        for rnd, v in ((1, 1000.0), (2, 500.0)):
            _suite(tmp_path, rnd, v)
        assert main(["--check", "--root", str(tmp_path)]) == 0
        out = json.loads((tmp_path / OUTPUT).read_text())
        assert list(out["series"].values())[0]["status"] == "short"


class TestConfig20Ingestion:
    """Config-20 bytes-per-commit envelope (PR 18): measured wire/h2d
    series gate lower-is-better; the planned column is a MODEL and is
    reported without gating."""

    def test_wire_bytes_per_leaf_direction_and_provenance(self, tmp_path):
        _suite(tmp_path, 1, 80.0, config=20,
               metric="lean_row_wire_bytes_per_leaf", unit="B/leaf",
               platform="xla-cpu-standin (no device leg)")
        points, skipped = load_artifacts(str(tmp_path))
        assert skipped == []
        assert points[0]["provenance"] == "xla-cpu-standin"
        out = build_trajectory(points, [])
        s = out["series"][
            "cfg=20|lean_row_wire_bytes_per_leaf|xla-cpu-standin"]
        assert s["direction"] == "lower"

    def test_h2d_bytes_blowup_fails_check(self, tmp_path):
        # the lean leg quietly shipping full rows again (2x the bytes)
        # is exactly the regression the sentinel must trip on
        for rnd, v in ((1, 67000.0), (2, 66500.0), (3, 67400.0),
                       (4, 140000.0)):
            _suite(tmp_path, rnd, v, config=20,
                   metric="lean_h2d_bytes_per_commit", unit="B/commit")
        assert main(["--check", "--root", str(tmp_path)]) == 1

    def test_modeled_series_reported_never_gated(self, tmp_path):
        # same blowup shape, but the metric is a model: unjudged, rc 0
        for rnd, v in ((1, 250000.0), (2, 255000.0), (3, 249000.0),
                       (4, 900000.0)):
            _suite(tmp_path, rnd, v, config=20,
                   metric="planned_modeled_bytes_per_commit",
                   unit="B/commit")
        assert main(["--check", "--root", str(tmp_path)]) == 0
        out = json.loads((tmp_path / OUTPUT).read_text())
        s = out["series"][
            "cfg=20|planned_modeled_bytes_per_commit|xla-cpu-standin"]
        assert s["status"] in ("short", "unjudged")


class TestCLIContract:
    def test_empty_checkout_skips_cleanly(self, tmp_path, capsys):
        assert main(["--check", "--root", str(tmp_path)]) == 0
        assert "nothing to check" in capsys.readouterr().out
        assert not (tmp_path / OUTPUT).exists()

    def test_output_is_deterministic(self, tmp_path):
        for rnd, v in ((1, 1000.0), (2, 1010.0), (3, 995.0)):
            _suite(tmp_path, rnd, v)
        assert main(["--root", str(tmp_path)]) == 0
        first = (tmp_path / OUTPUT).read_text()
        assert main(["--root", str(tmp_path)]) == 0
        assert (tmp_path / OUTPUT).read_text() == first

    def test_real_repo_artifacts_pass(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if not any(f.startswith("BENCH_") and f != OUTPUT
                   for f in os.listdir(repo)):
            return  # artifact-less checkout: nothing to assert
        points, _ = load_artifacts(repo)
        out = build_trajectory(points, [])
        assert out["regressions"] == []
        # every device leg carries a provenance tag
        assert all(s["provenance"] in
                   ("real-device", "xla-cpu-standin", "host_mode")
                   for s in out["series"].values())
