"""Conformance fixture runner (reference: tests/state_test_util.go driven
by tests/init.go's fork table).

Golden roots in tests/fixtures/ were frozen from a verified build; any
consensus-visible change (EVM gas rules, state transition, trie hashing,
fork lattice) that shifts a post-state root or log hash fails here with
the exact (test, fork) coordinate."""

import glob
import os

import pytest

from state_test_util import FIXTURE_DIR, FORKS, run_fixture_file

FIXTURES = sorted(glob.glob(os.path.join(FIXTURE_DIR, "*.json")))


def _all_entries():
    for path in FIXTURES:
        for name, fork, expect, got in run_fixture_file(path):
            yield os.path.basename(path), name, fork, expect, got


@pytest.mark.parametrize("fixture", [os.path.basename(p) for p in FIXTURES])
def test_fixture_file_roots_and_logs(fixture):
    path = os.path.join(FIXTURE_DIR, fixture)
    failures = []
    n = 0
    for name, fork, expect, got in run_fixture_file(path):
        n += 1
        if got != expect:
            failures.append(f"{name}/{fork}: want {expect} got {got}")
    assert n > 0, "fixture file contained no post entries"
    assert not failures, "\n".join(failures)


def test_generated_corpus_depth():
    """The generated corpus (tests/gen_fixtures.py over the semantic
    opcode vectors) must stay at GeneralStateTests-scale depth."""
    import json

    path = os.path.join(FIXTURE_DIR, "generated_state_tests.json")
    suite = json.load(open(path))
    assert len(suite) >= 450, f"generated corpus shrank: {len(suite)}"
    for case in suite.values():
        assert set(case["post"]) == {"Istanbul", "Cortina"}


def test_fixture_coverage_is_fork_sensitive():
    """The suite must actually exercise the fork lattice: at least one
    fixture diverges between Istanbul and an Apricot fork (else the
    harness is vacuous)."""
    path = os.path.join(FIXTURE_DIR, "general_state_tests.json")
    import json

    suite = json.load(open(path))
    assert any(
        case["post"]["Istanbul"]["root"] != case["post"]["ApricotPhase2"]["root"]
        for case in suite.values()
    )
    # and at least one fixture emits logs
    empty_logs = "0x1dcc4de8dec75d7aab85b567b6ccd41ad312451b948a7413f0a142fd40d49347"
    assert any(
        entry["logs"] != empty_logs
        for case in suite.values() for entry in case["post"].values()
    )
