"""Cross-commit device pipelining + template residency: pipelined
commits must be bit-exact vs the serial mirror, the C++ host executor
oracle, and the pure-Python reference trie, across accept/reject/reorg
interleavings; a mid-pipeline device wedge must land the whole in-flight
window on the host with identical roots (the PR 6 soft landing, now
window-deep); the periodic spot-check must settle the window before
reading the device store back."""

import random

import pytest

from coreth_tpu import fault
from coreth_tpu.metrics import default_registry
from coreth_tpu.native.mpt import load_inc, plan_from_items
from coreth_tpu.trie.resident_mirror import MirrorError, ResidentAccountMirror
from coreth_tpu.trie.trie import Trie

pytestmark = pytest.mark.skipif(
    load_inc() is None, reason="native incremental planner unavailable")


@pytest.fixture(autouse=True)
def _pin_device_path(monkeypatch):
    # these oracle tests exercise the resident EXECUTOR; the CPU-backend
    # host fast path would silently bypass it on non-TPU test machines
    monkeypatch.setenv("CORETH_TPU_RESIDENT_HOST", "0")


@pytest.fixture(autouse=True)
def _clear_failpoints():
    yield
    fault.clear_all()


def _rand_items(rng, n):
    return {rng.randbytes(32): rng.randbytes(rng.randint(1, 90))
            for _ in range(n)}


def _oracle(state: dict) -> bytes:
    return plan_from_items(sorted(state.items())).execute_cpu()


def _py_oracle(state: dict) -> bytes:
    t = Trie()
    for k, v in sorted(state.items()):
        t.update(k, v)
    return t.hash()


def _apply(state: dict, batch):
    out = dict(state)
    for k, v in batch:
        if v:
            out[k] = v
        else:
            out.pop(k, None)
    return out


def _batch(rng, state, n):
    keys = list(state)
    out = []
    for _ in range(n):
        r = rng.random()
        if r < 0.5 and keys:
            out.append((rng.choice(keys), rng.randbytes(60)))
        elif r < 0.85:
            out.append((rng.randbytes(32), rng.randbytes(40)))
        elif keys:
            out.append((rng.choice(keys), b""))
    return out


def _hash(i: int) -> bytes:
    return bytes([i & 0xFF, (i >> 8) & 0xFF]) * 16


# ---- bit-exactness: pipelined vs serial vs both oracles -----------------


@pytest.mark.parametrize("depth", [1, 2])
def test_pipelined_linear_chain_matches_oracles(depth):
    """Every pipelined commit's deferred device-root compare passes when
    the header root is truthful, and the roots equal the C++ host
    executor oracle at every block plus the pure-Python reference trie
    at the endpoints."""
    rng = random.Random(1300 + depth)
    genesis = _rand_items(rng, 120)
    m = ResidentAccountMirror(sorted(genesis.items()),
                              pipeline_depth=depth)
    assert not m.host_mode and m._pipelining()
    assert m.root_of(m.GENESIS) == _oracle(genesis)
    assert m.root_of(m.GENESIS) == _py_oracle(genesis)

    state = genesis
    parent = m.GENESIS
    for i in range(1, 7):
        h = _hash(i)
        batch = _batch(rng, state, 10)
        state = _apply(state, batch)
        expected = _oracle(state)
        root = m.verify(parent, h, batch, expected_root=expected)
        assert root == expected, f"block {i}"
        if i % 3 == 0:
            m.accept(h)  # drains up to h; later dispatches keep flying
        parent = h
    # final settle: the full window's deferred compares must all pass
    m._drain_pipeline()
    assert m._inflight == []
    assert m.root_of(parent) == _oracle(state) == _py_oracle(state)
    # reads through the settled head agree with the model
    for k in list(state)[:10]:
        assert m.read(m.root_of(parent), k) == state[k]


@pytest.mark.parametrize("depth", [2])
def test_pipelined_fuzz_interleaved_lifecycle(depth, monkeypatch):
    """Seeded fuzz over an N-commit chain with interleaved
    accept/reject/reorg: a pipelined device mirror and a serial host
    twin (the PR 6 oracle path) fed the identical op sequence stay
    root-identical at every step, both matching the host-executor
    oracle. The pipelined mirror's own deferred compares enforce
    device-root == header-root at every drain on top."""
    rng = random.Random(7700 + depth)
    genesis = _rand_items(rng, 100)
    # the serial twin runs host-mode: same lifecycle machinery, CPU
    # hashing — one device executor in the test, not two
    monkeypatch.setenv("CORETH_TPU_RESIDENT_HOST", "1")
    serial = ResidentAccountMirror(sorted(genesis.items()))
    monkeypatch.setenv("CORETH_TPU_RESIDENT_HOST", "0")
    pipe = ResidentAccountMirror(sorted(genesis.items()),
                                 pipeline_depth=depth)
    assert pipe._pipelining() and not serial._pipelining()

    states = {pipe.GENESIS: genesis}
    children = {}  # parent -> verified child hashes still alive
    alive = [pipe.GENESIS]
    nxt = 1
    for step in range(16):
        r = rng.random()
        if r < 0.60 or len(alive) == 1:
            # verify a new block on a random alive parent (non-head
            # parents exercise the reorg/branch-switch drain barrier)
            parent = rng.choice(alive)
            h = _hash(nxt)
            nxt += 1
            batch = _batch(rng, states[parent], 8)
            states[h] = _apply(states[parent], batch)
            expected = _oracle(states[h])
            got_p = pipe.verify(parent, h, batch, expected_root=expected)
            got_s = serial.verify(parent, h, batch)
            assert got_p == got_s == expected, f"step {step}"
            alive.append(h)
            children.setdefault(parent, []).append(h)
        elif r < 0.80:
            # reject a random non-genesis leaf (no verified children)
            leaves = [h for h in alive[1:] if not children.get(h)]
            if not leaves:
                continue
            h = rng.choice(leaves)
            try:
                pipe.reject(h)
                serial.reject(h)
            except MirrorError:
                continue  # accepted meanwhile; same answer both sides
            alive.remove(h)
            for c in children.values():
                if h in c:
                    c.remove(h)
        else:
            # accept the oldest unaccepted block on the canonical spine
            h = alive[1] if len(alive) > 1 else alive[0]
            if h in pipe._accepted:
                continue
            pipe.accept(h)
            serial.accept(h)
    pipe._drain_pipeline()
    assert pipe._inflight == []
    assert pipe.head == serial.head or (
        pipe.root_of(pipe.head) == serial.root_of(serial.head))
    for h in alive:
        assert pipe.root_of(h) == serial.root_of(h) == _oracle(states[h])


def test_pipeline_divergence_rewinds_and_recovers():
    """A commit recorded under a WRONG header root fails its deferred
    compare at the drain: the offending block (and its in-flight
    descendants) rewind, MirrorError surfaces, and the mirror keeps
    serving the surviving prefix with correct roots."""
    rng = random.Random(99)
    genesis = _rand_items(rng, 100)
    m = ResidentAccountMirror(sorted(genesis.items()), pipeline_depth=2)
    state = genesis

    b1 = _batch(rng, state, 10)
    s1 = _apply(state, b1)
    r1 = m.verify(m.GENESIS, _hash(1), b1, expected_root=_oracle(s1))

    b2 = _batch(rng, s1, 10)
    s2 = _apply(s1, b2)
    bogus = b"\xde\xad" * 16
    assert m.verify(_hash(1), _hash(2), b2, expected_root=bogus) == bogus

    before = default_registry.counter(
        "state/resident/pipeline_divergences").count()
    with pytest.raises(MirrorError):
        m._drain_pipeline()
    assert default_registry.counter(
        "state/resident/pipeline_divergences").count() == before + 1
    assert m._inflight == []
    # block 1 survived (its compare passed before the divergence);
    # block 2 is gone and the device image is back at block 1's state
    assert m.head == _hash(1)
    assert m.root_of(_hash(1)) == r1 == _oracle(s1)
    assert m.root_of(_hash(2)) is None
    assert not m.host_mode  # divergence is per-block, not a takeover
    # the same block re-verifies fine with its true root
    assert m.verify(_hash(1), _hash(2), b2,
                    expected_root=_oracle(s2)) == _oracle(s2)
    m._drain_pipeline()
    assert m.root_of(_hash(2)) == _oracle(s2)


# ---- spot-check vs in-flight window (the race regression) ---------------


def test_spot_check_settles_inflight_window_first():
    """Regression: spot_check used to read the device store back while
    pipelined commits were still in flight, cross-checking roots that
    had never been compared. It must drain (settling the deferred
    compares, per-block attribution) before touching the store."""
    rng = random.Random(55)
    genesis = _rand_items(rng, 100)
    m = ResidentAccountMirror(sorted(genesis.items()), pipeline_depth=2)
    state, parent = genesis, m.GENESIS
    for i in range(1, 3):
        batch = _batch(rng, state, 8)
        state = _apply(state, batch)
        m.verify(parent, _hash(i), batch, expected_root=_oracle(state))
        parent = _hash(i)
    assert len(m._inflight) > 0  # the window is genuinely populated
    assert m.spot_check() is True
    assert m._inflight == []  # drained, then cross-checked
    assert m.root_of(parent) == _oracle(state)


def test_spot_check_reports_inflight_divergence_as_failure():
    """If a block in the window was wrong, spot_check must report False
    (the chain quarantines) instead of mis-attributing the divergence
    to the device store image."""
    rng = random.Random(56)
    genesis = _rand_items(rng, 80)
    m = ResidentAccountMirror(sorted(genesis.items()), pipeline_depth=2)
    b1 = _batch(rng, genesis, 8)
    m.verify(m.GENESIS, _hash(1), b1, expected_root=b"\xbb" * 32)
    before = default_registry.counter(
        "state/resident/spot_check_failures").count()
    assert m.spot_check() is False
    assert default_registry.counter(
        "state/resident/spot_check_failures").count() == before + 1
    assert m._inflight == []


# ---- failpoint drill: device hang mid-pipeline --------------------------


def test_mid_pipeline_hang_drains_on_host_bit_exact():
    """Deterministic drill (resident/before_absorb = hang): with two
    commits in flight, the device stops answering. The drain must take
    over on the host and recompute the ENTIRE window there, bit-exact
    against each block's header root, so callers never see the wedge."""
    rng = random.Random(77)
    genesis = _rand_items(rng, 100)
    # generous watchdog while XLA compiles the commit programs; tightened
    # right before the hang is armed so only the drill trips it
    m = ResidentAccountMirror(sorted(genesis.items()), pipeline_depth=2,
                              device_timeout=60.0)
    reasons = []
    m.on_takeover = reasons.append

    state, parent, expect = genesis, m.GENESIS, {}
    for i in range(1, 3):
        batch = _batch(rng, state, 12)
        state = _apply(state, batch)
        expect[_hash(i)] = _oracle(state)
        root = m.verify(parent, _hash(i), batch,
                        expected_root=expect[_hash(i)])
        assert root == expect[_hash(i)]
        parent = _hash(i)
    assert len(m._inflight) == 2

    m.device_timeout = 0.4
    fault.set_failpoint("resident/before_absorb", "hang")
    m.accept(_hash(1))  # drain hits the parked resolve -> wedge
    fault.clear_all()

    assert m.host_mode, "wedge mid-drain must land on the host"
    assert reasons, "on_takeover hook never fired"
    assert m._inflight == []
    # the host recompute of the window matched every header root
    for h, r in expect.items():
        assert m.root_of(h) == r
    assert m.head == _hash(2)
    # life goes on, CPU-resident: further commits stay oracle-exact
    batch = _batch(rng, state, 12)
    state = _apply(state, batch)
    assert m.verify(parent, _hash(3), batch) == _oracle(state)


def test_dispatch_wedge_lands_current_block_on_host():
    """A wedge at DISPATCH time (not drain): the current block's open
    scope sits on top of the window's scopes. The mirror must fold it
    away, land the window, then re-apply and commit this block on the
    host — returning its true root."""
    rng = random.Random(78)
    genesis = _rand_items(rng, 90)
    m = ResidentAccountMirror(sorted(genesis.items()), pipeline_depth=2,
                              device_timeout=60.0)
    b1 = _batch(rng, genesis, 10)
    s1 = _apply(genesis, b1)
    m.verify(m.GENESIS, _hash(1), b1, expected_root=_oracle(s1))

    # wedge the NEXT dispatch: its program sync (inside dispatch when a
    # watchdog is armed) parks on the failpoint
    m.device_timeout = 0.4
    fault.set_failpoint("resident/before_absorb", "hang")
    b2 = _batch(rng, s1, 10)
    s2 = _apply(s1, b2)
    root = m.verify(_hash(1), _hash(2), b2, expected_root=_oracle(s2))
    fault.clear_all()
    assert root == _oracle(s2)
    assert m.host_mode and m._inflight == []
    assert m.root_of(_hash(1)) == _oracle(s1)


# ---- template residency -------------------------------------------------


def test_template_residency_parity_and_instant_export():
    """Template commits (device re-zeroes/re-patches resident rows;
    uploads carry only fresh leaf content) produce bit-exact roots, and
    the per-commit digest absorb keeps the host cache warm: root() and
    spot_check work without a store readback."""
    rng = random.Random(31)
    genesis = _rand_items(rng, 120)
    m = ResidentAccountMirror(sorted(genesis.items()),
                              template_residency=True, pipeline_depth=2)
    assert m.template
    assert m.pipeline_depth == 0  # the absorb IS a sync; no pipelining
    assert not m._pipelining()
    assert m.root_of(m.GENESIS) == _oracle(genesis) == _py_oracle(genesis)

    state, parent = genesis, m.GENESIS
    for i in range(1, 5):
        batch = _batch(rng, state, 10)
        state = _apply(state, batch)
        # expected_root given but template forces the serial path
        root = m.verify(parent, _hash(i), batch,
                        expected_root=_oracle(state))
        assert root == _oracle(state), f"block {i}"
        parent = _hash(i)
    assert _py_oracle(state) == m.root_of(parent)
    # absorb kept the host digest cache current: root() is serviceable
    # without any device readback
    assert m.trie.root() == m.root_of(parent)
    assert m.spot_check() is True
    for k in list(state)[:8]:
        assert m.read(m.root_of(parent), k) == state[k]


def test_template_reorg_and_reject():
    """Branch switches under template residency: rollback + replay land
    on oracle-exact roots (replayed template commits re-absorb)."""
    rng = random.Random(32)
    genesis = _rand_items(rng, 100)
    m = ResidentAccountMirror(sorted(genesis.items()),
                              template_residency=True)
    b1 = _batch(rng, genesis, 10)
    s1 = _apply(genesis, b1)
    m.verify(m.GENESIS, _hash(1), b1)
    # sibling off genesis -> rewind through block 1, then replay back
    b2 = _batch(rng, genesis, 10)
    s2 = _apply(genesis, b2)
    assert m.verify(m.GENESIS, _hash(2), b2) == _oracle(s2)
    assert m.root_of(_hash(1)) == _oracle(s1)
    m.reject(_hash(2))
    b3 = _batch(rng, s1, 10)
    s3 = _apply(s1, b3)
    assert m.verify(_hash(1), _hash(3), b3) == _oracle(s3)
    assert m.trie.root() == _oracle(s3)


def test_template_wedge_takeover_drops_template_mode():
    """A wedged template commit takes over on the host; template mode
    ends with residency (host commits absorb by construction)."""
    rng = random.Random(33)
    genesis = _rand_items(rng, 100)
    m = ResidentAccountMirror(sorted(genesis.items()),
                              template_residency=True, device_timeout=60.0)
    assert m.template
    m.device_timeout = 0.4
    fault.set_failpoint("resident/before_absorb", "hang")
    b1 = _batch(rng, genesis, 10)
    s1 = _apply(genesis, b1)
    root = m.verify(m.GENESIS, _hash(1), b1)
    fault.clear_all()
    assert root == _oracle(s1)
    assert m.host_mode and not m.template
    b2 = _batch(rng, s1, 10)
    s2 = _apply(s1, b2)
    assert m.verify(_hash(1), _hash(2), b2) == _oracle(s2)


# ---- accounting: h2d bytes + overlap fraction ---------------------------


def test_h2d_counter_and_overlap_accounting():
    rng = random.Random(61)
    genesis = _rand_items(rng, 120)
    c = default_registry.counter("resident/h2d_bytes")
    before = c.count()
    m = ResidentAccountMirror(sorted(genesis.items()), pipeline_depth=1)
    assert c.count() > before  # the genesis commit uploaded something
    state, parent = genesis, m.GENESIS
    mid = c.count()
    for i in range(1, 4):
        batch = _batch(rng, state, 10)
        state = _apply(state, batch)
        m.verify(parent, _hash(i), batch, expected_root=_oracle(state))
        parent = _hash(i)
    m._drain_pipeline()
    assert c.count() > mid
    # at least one drained entry recorded its overlap (any value in
    # [0,1] is legitimate on a CPU stand-in backend)
    assert 0.0 <= m.last_overlap_fraction <= 1.0
    assert 0.0 <= default_registry.gauge(
        "resident/overlap_fraction").value() <= 1.0


def test_chain_flight_record_surfaces_pipeline_metrics():
    """Chain integration: with resident-pipeline-depth on, every block's
    flight record carries its exact h2d upload delta and (once the first
    drain lands) the measured overlap fraction — the per-block data
    debug_blockFlightRecord serves."""
    from coreth_tpu import params
    from coreth_tpu.consensus.dummy import new_dummy_engine
    from coreth_tpu.core.blockchain import BlockChain, CacheConfig
    from coreth_tpu.core.chain_makers import generate_chain
    from coreth_tpu.core.genesis import Genesis, GenesisAccount
    from coreth_tpu.core.types import Signer, Transaction
    from coreth_tpu.crypto.secp256k1 import priv_to_address
    from coreth_tpu.ethdb import MemoryDB
    from coreth_tpu.state.database import Database
    from coreth_tpu.trie.triedb import TrieDatabase

    key = b"\x11" * 32
    addr = priv_to_address(key)

    def make(resident, depth=0):
        diskdb = MemoryDB()
        return BlockChain(
            diskdb,
            CacheConfig(pruning=True, resident_account_trie=resident,
                        resident_prefer_host=False,
                        resident_pipeline_depth=depth),
            params.TEST_CHAIN_CONFIG,
            Genesis(config=params.TEST_CHAIN_CONFIG,
                    gas_limit=params.CORTINA_GAS_LIMIT,
                    alloc={addr: GenesisAccount(balance=10**22)}),
            new_dummy_engine(),
            state_database=Database(TrieDatabase(diskdb)),
        )

    signer = Signer(43112)

    def gen(i, bg):
        bf = bg.base_fee() or params.APRICOT_PHASE3_INITIAL_BASE_FEE
        tx = Transaction(type=2, chain_id=43112, nonce=i, max_fee=bf * 2,
                         max_priority_fee=0, gas=21000,
                         to=b"\x22" * 20, value=1000 + i)
        bg.add_tx(signer.sign(tx, key))

    default = make(resident=False)
    blocks, _ = generate_chain(default.config, default.current_block,
                               default.engine, default.state_database,
                               4, gen=gen)
    chain = make(resident=True, depth=1)
    try:
        assert chain.mirror is not None and chain.mirror.pipeline_depth == 1
        for b in blocks:
            chain.insert_block(b)  # raises on any root mismatch
        recs = chain.flight_recorder.last()
        assert recs
        assert any(
            r.get("counters", {}).get("resident/h2d_bytes", 0) > 0
            for r in recs), "per-block h2d delta never surfaced"
        assert any(
            "overlap_fraction" in r.get("resident", {}) for r in recs), \
            "overlap fraction never surfaced in a flight record"
        for b in blocks:
            chain.accept(b)
        chain.drain_acceptor_queue()
        assert chain.acceptor_error is None
        assert chain.mirror._inflight == []
    finally:
        chain.stop()
        default.stop()


def test_template_uploads_less_than_planned_full_rows():
    """The A/B the bench artifact records, in miniature: for an
    identical incremental batch, template residency's upload (fresh leaf
    content + patch tables, ~70 B/leaf) undercuts the planned device
    path's full dirty-node rows (~320 B/dirty node) — at identical
    roots."""
    from coreth_tpu.native.mpt import IncrementalTrie
    from coreth_tpu.ops.keccak_planned import default_planned_commit
    from coreth_tpu.ops.keccak_resident import ResidentExecutor

    rng = random.Random(62)
    genesis = _rand_items(rng, 250)
    # update-heavy batch on EXISTING keys: the dirty interior set (what
    # the planned path re-uploads whole) dwarfs the fresh-leaf payload
    keys = list(genesis)
    batch = [(rng.choice(keys), rng.randbytes(60)) for _ in range(30)]
    final = _apply(genesis, batch)

    planned_trie = IncrementalTrie(sorted(genesis.items()))
    planned_trie.commit_cpu()
    planned_trie.update(batch)
    planned = default_planned_commit()
    planned_root = planned_trie.commit_device(planned)
    planned_bytes = planned.last_h2d_bytes

    c = default_registry.counter("resident/h2d_bytes")
    tmpl_trie = IncrementalTrie(sorted(genesis.items()))
    ex = ResidentExecutor()
    tmpl_trie.commit_template(ex)  # genesis upload (not measured)
    tmpl_trie.update(batch)
    b0 = c.count()
    tmpl_root = tmpl_trie.commit_template(ex)
    tmpl_bytes = c.count() - b0

    assert planned_root == tmpl_root == _oracle(final)
    assert 0 < tmpl_bytes < planned_bytes
