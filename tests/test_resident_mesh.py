"""Mesh-sharded resident commit (the promoted 8-device dryrun):
store/arena rows sharded PartitionSpec('batch', None) across the virtual
CPU mesh (tests/conftest.py forces 8 host devices) must be bit-exact vs
the C++ host executor oracle and the pure-Python reference trie at every
width in {1, 2, 4, 8}, through rollback/reject, reorg, pipelining, and
the NEW degradation-ladder rung: a wedge on a mesh-sharded executor
demotes to a single-device resident rebuild (host-oracle-anchored)
before the one-way host takeover."""

import random
import threading

import pytest

from coreth_tpu import fault
from coreth_tpu.metrics import default_registry
from coreth_tpu.native.mpt import (DeviceWedgedError, load_inc,
                                   plan_from_items)
from coreth_tpu.trie.resident_mirror import ResidentAccountMirror
from coreth_tpu.trie.trie import Trie

pytestmark = pytest.mark.skipif(
    load_inc() is None, reason="native incremental planner unavailable")

WIDTHS = (1, 2, 4, 8)


@pytest.fixture(autouse=True)
def _pin_device_path(monkeypatch):
    # mesh sharding lives in the resident EXECUTOR; the CPU-backend host
    # fast path would silently bypass it on non-TPU test machines
    monkeypatch.setenv("CORETH_TPU_RESIDENT_HOST", "0")


@pytest.fixture(autouse=True)
def _clear_failpoints():
    yield
    fault.clear_all()


def _rand_items(rng, n):
    return {rng.randbytes(32): rng.randbytes(rng.randint(1, 90))
            for _ in range(n)}


def _oracle(state: dict) -> bytes:
    return plan_from_items(sorted(state.items())).execute_cpu()


def _py_oracle(state: dict) -> bytes:
    t = Trie()
    for k, v in sorted(state.items()):
        t.update(k, v)
    return t.hash()


def _apply(state: dict, batch):
    out = dict(state)
    for k, v in batch:
        if v:
            out[k] = v
        else:
            out.pop(k, None)
    return out


def _batch(rng, state, n):
    keys = list(state)
    out = []
    for _ in range(n):
        r = rng.random()
        if r < 0.5 and keys:
            out.append((rng.choice(keys), rng.randbytes(60)))
        elif r < 0.85:
            out.append((rng.randbytes(32), rng.randbytes(40)))
        elif keys:
            out.append((rng.choice(keys), b""))
    return out


def _hash(i: int) -> bytes:
    return bytes([i & 0xFF, (i >> 8) & 0xFF]) * 16


class _Wedgy:
    """Proxies the mirror's executor; when armed, the next run() raises
    DeviceWedgedError once — an instant wedge that leaves the watchdog
    budget intact for the demotion's single-device rebuild."""

    def __init__(self, real):
        object.__setattr__(self, "_real", real)
        object.__setattr__(self, "wedge_next", False)

    def run(self, export):
        if self.wedge_next:
            object.__setattr__(self, "wedge_next", False)
            raise DeviceWedgedError("injected mesh wedge")
        return self._real.run(export)

    def __getattr__(self, name):
        return getattr(self._real, name)

    def __setattr__(self, name, value):
        if name == "wedge_next":
            object.__setattr__(self, name, value)
        else:
            setattr(self._real, name, value)


# ---- bit-exactness across the width sweep -------------------------------


@pytest.mark.parametrize("width", WIDTHS)
def test_mesh_width_matches_oracles(width):
    """Linear chain + reject + reorg at every mesh width: roots equal
    the C++ host oracle at every block and the pure-Python trie at the
    endpoints; the executor really is sharded [width] ways."""
    rng = random.Random(4100 + width)
    genesis = _rand_items(rng, 100)
    m = ResidentAccountMirror(sorted(genesis.items()), mesh_devices=width)
    assert not m.host_mode and m.shards == width
    if width > 1:
        # the store must actually live on [width] devices
        assert len(m.ex.store.sharding.device_set) == width
    assert m.root_of(m.GENESIS) == _oracle(genesis) == _py_oracle(genesis)

    state, parent = genesis, m.GENESIS
    states = {parent: genesis}
    for i in range(1, 5):
        h = _hash(i)
        batch = _batch(rng, state, 8)
        state = _apply(state, batch)
        states[h] = state
        assert m.verify(parent, h, batch) == _oracle(state), f"block {i}"
        parent = h
    # reject the head (rollback on the sharded image)
    m.reject(_hash(4))
    assert m.root_of(_hash(3)) == _oracle(states[_hash(3)])
    # reorg: a sibling of block 3 on top of block 2 (rewind + replay)
    fork = _batch(rng, states[_hash(2)], 8)
    fork_state = _apply(states[_hash(2)], fork)
    assert m.verify(_hash(2), _hash(99), fork) == _oracle(fork_state)
    assert m.root_of(_hash(99)) == _py_oracle(fork_state)
    # gather accounting (PR 18 provenance split): the MEASURED counter
    # stays 0 — the mirror's commit path never materializes the
    # replicated dig matrix host-side — while the MODELED cross-shard
    # cost is nonzero exactly when sharded; the per-shard lane histogram
    # sums to the commit
    assert m.ex.last_gather_bytes == 0
    if width == 1:
        assert m.ex.last_gather_bytes_modeled == 0
        assert len(m.ex.last_shard_lanes) == 1
    else:
        assert m.ex.last_gather_bytes_modeled > 0
        assert len(m.ex.last_shard_lanes) == width
    assert sum(m.ex.last_shard_lanes) > 0


def test_mesh_mid_window_host_landing():
    """A wedge while a depth-2 pipeline window is in flight on an
    8-shard mesh: the whole window must land bit-exactly on a LOWER
    rung (single-device resident when the rebuild beats the watchdog,
    host otherwise — both are correct ladder landings)."""
    rng = random.Random(4200)
    genesis = _rand_items(rng, 100)
    m = ResidentAccountMirror(sorted(genesis.items()), mesh_devices=8,
                              pipeline_depth=2, device_timeout=60.0)
    assert m._pipelining() and m.shards == 8
    state, parent = genesis, m.GENESIS
    expected = {}
    for i in range(1, 3):
        h = _hash(i)
        batch = _batch(rng, state, 8)
        state = _apply(state, batch)
        expected[h] = _oracle(state)
        assert m.verify(parent, h, batch,
                        expected_root=expected[h]) == expected[h]
        parent = h
    assert m._inflight  # a window is genuinely in flight
    # wedge the drain: the dispatched commits' resolve() hangs, the
    # watchdog fires, and _drain_on_host lands the window one rung down
    fault.set_failpoint("resident/before_absorb", "hang")
    m.device_timeout = 0.4
    m._drain_pipeline()
    fault.clear_all()
    m.device_timeout = 60.0
    assert m._inflight == []
    assert m.shards < 8, "the mesh rung must have been abandoned"
    for h, root in expected.items():
        assert m.root_of(h) == root
    # the landing rung keeps serving: another block, still bit-exact
    batch = _batch(rng, state, 8)
    state = _apply(state, batch)
    assert m.verify(parent, _hash(3), batch) == _oracle(state)


# ---- the mesh -> single-device -> host ladder ---------------------------


def test_mesh_ladder_demotion_bit_exact():
    """The new ladder rung end to end: first wedge demotes the 8-shard
    mesh to a single-device resident rebuild (host_mode stays False, no
    takeover counted, roots bit-exact); second wedge walks the last
    rung to the host. Every root along the way equals the oracle."""
    rng = random.Random(4300)
    genesis = _rand_items(rng, 120)
    m = ResidentAccountMirror(sorted(genesis.items()), mesh_devices=8)
    assert m.shards == 8
    state = genesis
    b1 = _batch(rng, state, 10)
    s1 = _apply(state, b1)
    assert m.verify(m.GENESIS, _hash(1), b1) == _oracle(s1)

    w = _Wedgy(m.ex)
    m.ex = w
    dem0 = default_registry.counter(
        "state/resident/mesh_demotions").count()
    to0 = default_registry.counter(
        "state/resident/device_takeovers").count()

    w.wedge_next = True
    b2 = _batch(rng, s1, 10)
    s2 = _apply(s1, b2)
    assert m.verify(_hash(1), _hash(2), b2) == _oracle(s2)
    assert not m.host_mode, "mesh wedge must demote, not take over"
    assert m.shards == 1
    assert default_registry.counter(
        "state/resident/mesh_demotions").count() == dem0 + 1
    assert default_registry.counter(
        "state/resident/device_takeovers").count() == to0

    # the single-device rung keeps committing bit-exactly
    b3 = _batch(rng, s2, 10)
    s3 = _apply(s2, b3)
    assert m.verify(_hash(2), _hash(3), b3) == _oracle(s3)
    # rollback across the demotion boundary: reject back to block 2
    m.reject(_hash(3))
    assert m.root_of(_hash(2)) == _oracle(s2)

    # second wedge: bottom device rung -> host (the PR 6 landing)
    w2 = _Wedgy(m.ex)
    m.ex = w2
    w2.wedge_next = True
    b4 = _batch(rng, s2, 10)
    s4 = _apply(s2, b4)
    assert m.verify(_hash(2), _hash(4), b4) == _oracle(s4)
    assert m.host_mode and m.shards == 1
    assert default_registry.counter(
        "state/resident/device_takeovers").count() == to0 + 1
    assert m.root_of(_hash(4)) == _py_oracle(s4)


def test_mesh_demotion_rebuild_wedge_escalates_to_host():
    """When the single-device rebuild inside the demotion ALSO wedges
    (a dead backend, not a dead mesh), the ladder walks straight
    through to the host with the same commit still answered
    bit-exactly."""
    rng = random.Random(4400)
    genesis = _rand_items(rng, 100)
    m = ResidentAccountMirror(sorted(genesis.items()), mesh_devices=8,
                              device_timeout=60.0)
    state = genesis
    b1 = _batch(rng, state, 8)
    s1 = _apply(state, b1)
    assert m.verify(m.GENESIS, _hash(1), b1) == _oracle(s1)
    # a hanging d2h sync + a watchdog too tight for any rebuild: the
    # demotion's own recommit wedges, _demote_mesh returns False, and
    # the host takeover finishes the job
    fail0 = default_registry.counter(
        "state/resident/mesh_demotion_failures").count()

    class _Hang:
        def run(self, export):
            threading.Event().wait()

        def __getattr__(self, name):
            return getattr(m_ex, name)

        def __setattr__(self, name, value):
            setattr(m_ex, name, value)

    m_ex = m.ex
    m.ex = _Hang()
    m.device_timeout = 0.2
    b2 = _batch(rng, s1, 8)
    s2 = _apply(s1, b2)
    assert m.verify(_hash(1), _hash(2), b2) == _oracle(s2)
    assert m.host_mode
    assert default_registry.counter(
        "state/resident/mesh_demotion_failures").count() == fail0 + 1


# ---- mesh + pipeline fuzz vs the serial host twin (satellite 5) ---------


def test_mesh_pipeline_fuzz_vs_host_twin(monkeypatch):
    """Seeded lifecycle fuzz (verify/reject/accept on random parents —
    reorgs ride the branch switches) at pipeline depth 2 over an
    8-shard mesh vs a serial host-twin mirror fed the identical op
    sequence: root-identical at every step, both matching the host
    executor oracle."""
    rng = random.Random(8800)
    genesis = _rand_items(rng, 100)
    monkeypatch.setenv("CORETH_TPU_RESIDENT_HOST", "1")
    serial = ResidentAccountMirror(sorted(genesis.items()))
    monkeypatch.setenv("CORETH_TPU_RESIDENT_HOST", "0")
    mesh = ResidentAccountMirror(sorted(genesis.items()),
                                 mesh_devices=8, pipeline_depth=2)
    assert mesh._pipelining() and mesh.shards == 8
    assert not serial._pipelining()

    states = {mesh.GENESIS: genesis}
    children = {}
    alive = [mesh.GENESIS]
    nxt = 1
    for step in range(12):
        r = rng.random()
        if r < 0.60 or len(alive) == 1:
            parent = rng.choice(alive)
            h = _hash(nxt)
            nxt += 1
            batch = _batch(rng, states[parent], 8)
            states[h] = _apply(states[parent], batch)
            expected = _oracle(states[h])
            got_m = mesh.verify(parent, h, batch, expected_root=expected)
            got_s = serial.verify(parent, h, batch)
            assert got_m == got_s == expected, f"step {step}"
            alive.append(h)
            children.setdefault(parent, []).append(h)
        elif r < 0.80:
            leaves = [h for h in alive[1:] if not children.get(h)]
            if not leaves:
                continue
            h = rng.choice(leaves)
            mesh.reject(h)
            serial.reject(h)
            alive.remove(h)
            for c in children.values():
                if h in c:
                    c.remove(h)
        else:
            # periodic spot-check settles the window and cross-checks
            # the sharded store against the host keccak oracle
            assert mesh.spot_check()
    mesh._drain_pipeline()
    assert mesh._inflight == []
    assert not mesh.host_mode and mesh.shards == 8
    for h in alive:
        assert mesh.root_of(h) == serial.root_of(h) == _oracle(states[h])


# ---- chain-level flight record (un-ragged keys) -------------------------


def test_chain_flight_record_mesh_keys_unragged():
    """Every insert's flight record must carry resident/shards and
    resident/gather_bytes EXPLICITLY — an unsharded (here host-mode)
    chain says shards=1 / gather_bytes=0 rather than omitting the keys,
    the PR 12 h2d discipline extended to the mesh columns."""
    from coreth_tpu import params
    from coreth_tpu.consensus.dummy import new_dummy_engine
    from coreth_tpu.core.blockchain import BlockChain, CacheConfig
    from coreth_tpu.core.chain_makers import generate_chain
    from coreth_tpu.core.genesis import Genesis, GenesisAccount
    from coreth_tpu.core.types import Signer, Transaction
    from coreth_tpu.crypto.secp256k1 import priv_to_address
    from coreth_tpu.ethdb import MemoryDB
    from coreth_tpu.state.database import Database
    from coreth_tpu.trie.triedb import TrieDatabase

    key = b"\x11" * 32
    addr = priv_to_address(key)
    diskdb = MemoryDB()
    chain = BlockChain(
        diskdb,
        CacheConfig(pruning=True, resident_account_trie=True,
                    resident_prefer_host=True),  # cheap CPU-only legs
        params.TEST_CHAIN_CONFIG,
        Genesis(config=params.TEST_CHAIN_CONFIG,
                gas_limit=params.CORTINA_GAS_LIMIT,
                alloc={addr: GenesisAccount(balance=10**22)}),
        new_dummy_engine(),
        state_database=Database(TrieDatabase(diskdb)),
    )
    signer = Signer(43112)

    def gen(i, bg):
        bf = bg.base_fee() or params.APRICOT_PHASE3_INITIAL_BASE_FEE
        tx = Transaction(type=2, chain_id=43112, nonce=i, max_fee=bf * 2,
                         max_priority_fee=0, gas=21000,
                         to=b"\x22" * 20, value=1000 + i)
        bg.add_tx(signer.sign(tx, key))

    try:
        blocks, _ = generate_chain(chain.config, chain.current_block,
                                   chain.engine, chain.state_database,
                                   2, gen=gen)
        for b in blocks:
            chain.insert_block(b)
        recs = chain.flight_recorder.last()
        assert recs
        for r in recs:
            assert r["resident"]["shards"] == 1
            assert r["counters"]["resident/gather_bytes"] == 0
            assert r["counters"]["resident/gather_bytes_modeled"] == 0
            assert "resident/absorb_d2h_bytes" in r["counters"]
            assert "resident/lean_wire_bytes" in r["counters"]
            assert "resident/h2d_bytes" in r["counters"]
    finally:
        chain.stop()
