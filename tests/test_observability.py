"""Observability layer tests (ISSUE 5): Prometheus exposition validity,
flight-recorder ring semantics, span parent/ordering invariants under
concurrency, metric lock discipline under races, the JSON log formatter,
the stdlib /metrics + /healthz endpoint, and the debug RPC surface."""

import json
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import pytest

from coreth_tpu.metrics import (Registry, Timer, sanitize_metric_name)
from coreth_tpu.metrics import spans as spans_mod
from coreth_tpu.metrics.__main__ import validate_exposition
from coreth_tpu.metrics.flight import (DEFAULT_CAPACITY, FlightRecorder,
                                       marshal_record)
from coreth_tpu.metrics.http import (PROMETHEUS_CONTENT_TYPE,
                                     MetricsHTTPServer)
from coreth_tpu.metrics.spans import Tracer, _NULL_SPAN, span


# ---------------------------------------------------------------- exposition

def _populated_registry() -> Registry:
    reg = Registry()
    reg.counter("chain/inserts").inc(7)
    reg.gauge("chain/head.height").update(42)
    reg.meter("rpc/requests").mark(3)
    h = reg.histogram("trie/keccak/batch_size")
    for i in range(100):
        h.update(i)
    t = reg.timer("chain/phase/execute")
    for i in range(50):
        t.update(0.001 * (i + 1))
    reg.timer("never/updated")  # zero-sample summary must still be legal
    return reg


class TestPrometheusExposition:
    def test_export_is_parser_clean(self):
        text = _populated_registry().export_prometheus()
        assert validate_exposition(text) == []

    def test_empty_registry_is_parser_clean(self):
        assert validate_exposition(Registry().export_prometheus()) == []

    def test_timer_summary_shape(self):
        text = _populated_registry().export_prometheus()
        fam = "chain_phase_execute_seconds"
        assert f"# TYPE {fam} summary" in text
        assert f"# HELP {fam} " in text
        assert f'{fam}{{quantile="0.5"}}' in text
        assert f'{fam}{{quantile="0.99"}}' in text
        assert f"{fam}_count 50" in text

    def test_timer_quantiles_monotone_and_sum_exact(self):
        reg = Registry()
        t = reg.timer("q/test")
        for i in range(200):
            t.update(float(i))
        text = reg.export_prometheus()
        qs = {}
        total = None
        for line in text.splitlines():
            if line.startswith('q_test_seconds{quantile='):
                label = line.split('"')[1]
                qs[label] = float(line.rsplit(" ", 1)[1])
            elif line.startswith("q_test_seconds_sum "):
                total = float(line.rsplit(" ", 1)[1])
        assert qs["0.5"] <= qs["0.9"] <= qs["0.99"]
        assert total == sum(float(i) for i in range(200))

    def test_hostile_names_sanitized(self):
        assert sanitize_metric_name("chain/head.height") == "chain_head_height"
        assert sanitize_metric_name("9starts") == "_9starts"
        assert sanitize_metric_name("resident/fill+ratio") == \
            "resident_fill_ratio"
        # ":" is legal Prometheus but reserved for recording rules; the
        # profiler's "<lock:Owner.attr>" tags must flatten like any other
        # hostile character, so the sanitizer folds it too (PR 20)
        assert sanitize_metric_name("ok:name_1") == "ok_name_1"
        assert sanitize_metric_name("lock/BlockChain.chainmu/wait_seconds") \
            == "lock_BlockChain_chainmu_wait_seconds"

    def test_validator_rejects_malformed(self):
        bad = "# TYPE x counter\nx{quantile=0.5 nope\n"
        assert validate_exposition(bad) != []
        # sample without a preceding TYPE
        assert validate_exposition("orphan 1\n") != []
        # non-monotone summary quantiles
        assert validate_exposition(
            "# HELP s s\n# TYPE s summary\n"
            's{quantile="0.5"} 9\ns{quantile="0.9"} 1\n'
            "s_sum 10\ns_count 2\n") != []

    def test_check_cli_passes(self):
        out = subprocess.run(
            [sys.executable, "-m", "coreth_tpu.metrics", "--check"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr


# ---------------------------------------------------------------- metric races

class TestMetricRaces:
    def test_timer_total_exact_under_threads(self):
        t = Timer()
        n_threads, per = 8, 2500

        def work():
            for _ in range(per):
                t.update(1.0)  # 1.0 is exact in binary: lost updates show

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert t.total() == float(n_threads * per)
        assert t.count() == n_threads * per
        assert t.hist.count() == n_threads * per

    def test_gauge_update_under_threads(self):
        reg = Registry()
        g = reg.gauge("race/gauge")
        vals = list(range(1, 9))

        def work(v):
            for _ in range(2000):
                g.update(v)

        threads = [threading.Thread(target=work, args=(v,)) for v in vals]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert g.value() in vals  # last-writer-wins, never torn


# ---------------------------------------------------------------- flight ring

class TestFlightRecorder:
    def test_ring_is_bounded(self):
        fr = FlightRecorder(capacity=4)
        for i in range(10):
            fr.record({"number": i, "hash": bytes([i]) * 32})
        assert len(fr) == 4
        assert fr.capacity() == 4
        nums = [r["number"] for r in fr.last()]
        assert nums == [6, 7, 8, 9]  # newest-last, oldest evicted

    def test_default_capacity(self):
        assert FlightRecorder().capacity() == DEFAULT_CAPACITY

    def test_seq_monotone_and_accept_marking(self):
        fr = FlightRecorder(capacity=8)
        h1, h2 = b"\x01" * 32, b"\x02" * 32
        fr.record({"number": 1, "hash": h1})
        fr.record({"number": 2, "hash": h2})
        seqs = [r["seq"] for r in fr.last()]
        assert seqs == sorted(seqs) and len(set(seqs)) == 2
        assert all(not r["accepted"] for r in fr.last())
        fr.mark_accepted(h1)
        accepted = fr.last(accepted_only=True)
        assert [r["number"] for r in accepted] == [1]
        assert fr.find(h2)["accepted"] is False
        assert fr.find(b"\xff" * 32) is None

    def test_last_n_slices_newest(self):
        fr = FlightRecorder(capacity=8)
        for i in range(5):
            fr.record({"number": i, "hash": bytes([i]) * 32})
        assert [r["number"] for r in fr.last(n=2)] == [3, 4]

    def test_marshal_record_json_safe(self):
        rec = {"number": 3, "hash": b"\xab" * 32, "txs": 5,
               "phases": {"verify": 0.1}, "counters": {"c": 2},
               "resident": {}, "accepted": True}
        out = marshal_record(rec)
        assert out["hash"] == "0x" + "ab" * 32
        assert out["phases"] is not rec["phases"]  # copies nested dicts
        json.dumps(out)  # round-trips

    def test_concurrent_record_keeps_bounds_and_unique_seqs(self):
        fr = FlightRecorder(capacity=32)

        def work(base):
            for i in range(200):
                fr.record({"number": base + i, "hash": b"\x00" * 32})

        threads = [threading.Thread(target=work, args=(b * 1000,))
                   for b in range(6)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        recs = fr.last()
        assert len(recs) == 32
        seqs = [r["seq"] for r in recs]
        assert seqs == sorted(seqs) and len(set(seqs)) == 32
        assert max(seqs) == 6 * 200


# ---------------------------------------------------------------- spans

class TestSpans:
    def test_disabled_returns_shared_null_span(self):
        assert not spans_mod.enabled  # tests run with spans off
        s = span("chain/insert", number=1)
        assert s is _NULL_SPAN
        assert span("other") is s  # no allocation per call
        with s:
            s.set_attr("ignored", 1)

    def test_parenting_and_ordering(self):
        tr = Tracer(capacity=16)
        with tr.span("chain/insert") as outer:
            with tr.span("chain/verify") as inner:
                assert tr.current() is inner
            assert tr.current() is outer
        assert tr.current() is None
        done = tr.snapshot()
        assert [s.name for s in done] == ["chain/verify", "chain/insert"]
        verify, insert = done
        assert verify.parent_id == insert.span_id
        assert insert.parent_id is None
        assert verify.start >= insert.start
        assert verify.end <= insert.end

    def test_exception_annotates_and_unwinds(self):
        tr = Tracer(capacity=16)
        with pytest.raises(ValueError):
            with tr.span("chain/insert"):
                with tr.span("chain/verify"):
                    raise ValueError("boom")
        assert tr.current() is None
        by_name = {s.name: s for s in tr.snapshot()}
        assert by_name["chain/verify"].attrs["error"] == "ValueError"
        assert by_name["chain/insert"].attrs["error"] == "ValueError"

    def test_ring_bounded_and_resizable(self):
        tr = Tracer(capacity=4)
        for i in range(10):
            with tr.span(f"s{i}"):
                pass
        assert len(tr.snapshot()) == 4
        tr.set_capacity(2)
        assert tr.capacity() == 2
        assert len(tr.snapshot()) == 2

    def test_thread_stacks_do_not_cross(self):
        tr = Tracer(capacity=256)
        barrier = threading.Barrier(4)

        def work(i):
            with tr.span(f"root/{i}"):
                barrier.wait(timeout=10)  # all roots open simultaneously
                with tr.span(f"child/{i}"):
                    pass

        threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        spans = tr.snapshot()
        assert len(spans) == 8
        roots = {s.name.split("/")[1]: s for s in spans
                 if s.name.startswith("root/")}
        for s in spans:
            if s.name.startswith("child/"):
                i = s.name.split("/")[1]
                # parented under the SAME thread's root, despite all four
                # roots being open concurrently
                assert s.parent_id == roots[i].span_id
                assert s.tid == roots[i].tid

    def test_chrome_trace_shape(self):
        tr = Tracer(capacity=16)
        with tr.span("chain/insert", number=7):
            pass
        trace = tr.chrome_trace()
        (ev,) = trace["traceEvents"]
        assert ev["ph"] == "X"
        assert ev["cat"] == "chain"
        assert ev["args"]["number"] == 7
        assert ev["dur"] >= 0 and ev["ts"] >= 0
        json.dumps(trace)
        # clear=True drains the ring
        tr.chrome_trace(clear=True)
        assert tr.snapshot() == []

    def test_set_enabled_toggles_module_gate(self):
        assert not spans_mod.enabled
        spans_mod.set_enabled(True)
        try:
            s = span("toggle/test")
            assert s is not _NULL_SPAN
            with s:
                pass
        finally:
            spans_mod.set_enabled(False)
        assert span("toggle/test") is _NULL_SPAN


# ---------------------------------------------------------------- logging

class TestLogFormatter:
    def _format(self, **kwargs):
        import logging

        from coreth_tpu.log import _JSONFormatter

        rec = logging.LogRecord("coreth_tpu.t", logging.ERROR, "f.py", 1,
                                kwargs.pop("msg", "it broke"), (), None)
        rec.__dict__.update(kwargs)
        return json.loads(_JSONFormatter().format(rec))

    def test_exc_field_on_exc_info(self):
        try:
            raise RuntimeError("kapow")
        except RuntimeError:
            out = self._format(exc_info=sys.exc_info())
        assert "RuntimeError: kapow" in out["exc"]
        assert "Traceback" in out["exc"]

    def test_no_exc_field_without_exc_info(self):
        assert "exc" not in self._format()

    def test_ctx_kwargs_merge(self):
        out = self._format(ctx={"block": 9, "hash": "0xab"})
        assert out["block"] == 9 and out["hash"] == "0xab"

    def test_leveled_ctx_helpers(self):
        import io
        import logging

        from coreth_tpu import log as clog

        stream = io.StringIO()
        clog.init(level="debug", json_format=True, stream=stream)
        try:
            lg = clog.get_logger("obs_test")
            clog.debug(lg, "d", a=1)
            clog.info(lg, "i", b=2)
            clog.warn(lg, "w", c=3)
            try:
                raise ValueError("inner")
            except ValueError:
                clog.error(lg, "e", exc_info=sys.exc_info(), d=4)
            lines = [json.loads(l) for l in
                     stream.getvalue().strip().splitlines()]
        finally:
            clog.init(level="info", json_format=False)
        assert [l["lvl"] for l in lines] == ["debug", "info", "warning",
                                             "error"]
        assert lines[0]["a"] == 1 and lines[2]["c"] == 3
        assert "ValueError: inner" in lines[3]["exc"]


# ---------------------------------------------------------------- HTTP endpoint

@pytest.fixture
def http_server():
    reg = Registry()
    reg.counter("http/test/hits").inc(3)
    reg.timer("http/test/lat").update(0.5)
    health = {"healthy": True}
    srv = MetricsHTTPServer(registry=reg, health_fn=lambda: dict(health))
    port = srv.start(host="127.0.0.1", port=0)
    yield srv, port, health
    srv.stop()


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
            return resp.status, resp.headers, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


class TestMetricsHTTP:
    def test_metrics_endpoint_parser_clean(self, http_server):
        _, port, _ = http_server
        status, headers, body = _get(port, "/metrics")
        assert status == 200
        assert headers["Content-Type"] == PROMETHEUS_CONTENT_TYPE
        assert int(headers["Content-Length"]) == len(body)
        text = body.decode()
        assert validate_exposition(text) == []
        assert "http_test_hits 3" in text
        assert "# TYPE http_test_lat_seconds summary" in text

    def test_healthz_flips_with_verdict(self, http_server):
        _, port, health = http_server
        status, _, body = _get(port, "/healthz")
        assert status == 200 and json.loads(body)["healthy"] is True
        health["healthy"] = False
        status, _, body = _get(port, "/healthz")
        assert status == 503 and json.loads(body)["healthy"] is False

    def test_unknown_path_404(self, http_server):
        _, port, _ = http_server
        assert _get(port, "/nope")[0] == 404
        assert _get(port, "/metrics/extra")[0] == 404

    def test_post_405(self, http_server):
        _, port, _ = http_server
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/metrics", data=b"x", method="POST")
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                status = resp.status
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 405

    def test_health_fn_crash_is_500_not_traceback(self):
        srv = MetricsHTTPServer(registry=Registry(),
                                health_fn=lambda: 1 // 0)
        port = srv.start(host="127.0.0.1", port=0)
        try:
            status, _, body = _get(port, "/healthz")
            assert status == 500
            assert b"Traceback" not in body
        finally:
            srv.stop()

    def test_stop_releases_port(self):
        srv = MetricsHTTPServer(registry=Registry())
        srv.start(host="127.0.0.1", port=0)
        srv.stop()
        assert srv.port is None


# ---------------------------------------------------------------- debug RPC

class _StubChain:
    def __init__(self):
        self.flight_recorder = FlightRecorder(capacity=8)


class _StubVM:
    def __init__(self):
        self.blockchain = _StubChain()


@pytest.fixture
def debug_server():
    from coreth_tpu.rpc.server import RPCServer
    from coreth_tpu.vm.api import DebugMetricsAPI

    vm = _StubVM()
    server = RPCServer()
    server.register_api("debug", DebugMetricsAPI(vm))
    yield vm, server
    server.stop()


def _rpc(server, method, *params):
    resp = json.loads(server.handle_raw(json.dumps(
        {"jsonrpc": "2.0", "id": 1, "method": method,
         "params": list(params)}).encode()))
    if "error" in resp:
        raise RuntimeError(resp["error"])
    return resp["result"]


class TestDebugRPC:
    def test_debug_metrics(self, debug_server):
        from coreth_tpu.metrics import default_registry

        default_registry.counter("rpc_obs/test").inc(2)
        _, server = debug_server
        out = _rpc(server, "debug_metrics")
        assert out["rpc_obs/test"] == {"type": "counter", "count": 2}

    def test_debug_block_flight_record(self, debug_server):
        vm, server = debug_server
        fr = vm.blockchain.flight_recorder
        fr.record({"number": 1, "hash": b"\x01" * 32, "txs": 2,
                   "phases": {"verify": 0.01}})
        fr.record({"number": 2, "hash": b"\x02" * 32, "txs": 3,
                   "phases": {"verify": 0.02}})
        fr.mark_accepted(b"\x02" * 32)
        accepted = _rpc(server, "debug_blockFlightRecord")
        assert [r["number"] for r in accepted] == [2]
        assert accepted[0]["hash"] == "0x" + "02" * 32
        everything = _rpc(server, "debug_blockFlightRecord", None, False)
        assert [r["number"] for r in everything] == [1, 2]

    def test_debug_span_dump_and_toggle(self, debug_server):
        _, server = debug_server
        assert _rpc(server, "debug_setSpans", True) is True
        try:
            with span("rpc_obs/traced"):
                pass
            trace = _rpc(server, "debug_spanDump")
            assert any(ev["name"] == "rpc_obs/traced"
                       for ev in trace["traceEvents"])
        finally:
            assert _rpc(server, "debug_setSpans", False) is False

    def test_debug_set_expensive_metrics(self, debug_server):
        from coreth_tpu import metrics as m

        _, server = debug_server
        before = m.enabled_expensive
        try:
            assert _rpc(server, "debug_setExpensiveMetrics", True) is True
            assert m.enabled_expensive is True
            assert _rpc(server, "debug_setExpensiveMetrics", False) is False
        finally:
            m.enabled_expensive = before

    def test_debug_trace_request(self, debug_server):
        from coreth_tpu.metrics import tracectx

        _, server = debug_server
        ctx = tracectx.begin("rpc")
        assert ctx is not None
        ctx.meta["method"] = "eth_obsTest"
        tracectx.capture(ctx, "shed", note="unit")
        rec = _rpc(server, "debug_traceRequest", ctx.trace_id)
        assert rec["trace_id"] == ctx.trace_id
        assert rec["outcome"] == "shed"
        assert rec["meta"]["method"] == "eth_obsTest"
        listing = _rpc(server, "debug_traceRequest", None, 4)
        assert any(r["trace_id"] == ctx.trace_id for r in listing)
        with pytest.raises(RuntimeError, match="not captured"):
            _rpc(server, "debug_traceRequest", "rpc-dead-beef")

    def test_debug_slo_status_tolerates_stub_vm(self, debug_server):
        from coreth_tpu.metrics import observe_slo

        _, server = debug_server
        observe_slo("slo/rpc/eth_obsSlo", 0.003, "rpc-obs-000001")
        status = _rpc(server, "debug_sloStatus")
        assert status["rpcSloBudget"] is None  # stub vm: no rpc server
        s = status["series"]["slo/rpc/eth_obsSlo"]
        assert s["count"] >= 1 and s["p50"] >= 0.0


# ------------------------------------------------------- SLO histograms

class TestSLOHistograms:
    def test_bucketed_histogram_exports_histogram_family(self):
        from coreth_tpu.metrics import DEFAULT_SLO_BUCKETS

        reg = Registry()
        h = reg.histogram("slo/rpc/eth_call", buckets=DEFAULT_SLO_BUCKETS)
        for i in range(40):
            h.update(0.004 * (i % 10), exemplar="rpc-test-%06x" % i)
        h.update(99.0, exemplar="rpc-test-top")  # above the top bucket
        text = reg.export_prometheus()
        assert validate_exposition(text) == []
        fam = "slo_rpc_eth_call"
        assert f"# TYPE {fam} histogram" in text
        assert f'{fam}_bucket{{le="+Inf"}} 41' in text
        assert f"{fam}_count 41" in text
        # cumulative counts are monotone over sorted bounds
        cums = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
                if line.startswith(f"{fam}_bucket")]
        assert cums == sorted(cums)
        # exemplar comment lines carry trace ids per bucket
        assert "# EXEMPLAR " in text and "trace_id=rpc-test-" in text

    def test_plain_histogram_stays_summary(self):
        reg = Registry()
        reg.histogram("plain/h").update(1.0)
        text = reg.export_prometheus()
        assert "# TYPE plain_h summary" in text
        assert "plain_h_bucket" not in text

    def test_exemplar_value_within_bucket_bound(self):
        reg = Registry()
        h = reg.histogram("slo/x", buckets=(0.1, 1.0))
        h.update(0.05, exemplar="t-low")
        h.update(0.5, exemplar="t-mid")
        ex = h.exemplars()
        assert ex["0.1"]["trace_id"] == "t-low"
        assert ex["0.1"]["value"] <= 0.1
        assert ex["1.0"]["trace_id"] == "t-mid"

    def test_validator_rejects_non_monotone_buckets(self):
        bad = ("# HELP h h\n# TYPE h histogram\n"
               'h_bucket{le="0.1"} 5\nh_bucket{le="1.0"} 3\n'
               'h_bucket{le="+Inf"} 3\nh_sum 1\nh_count 3\n')
        assert validate_exposition(bad) != []

    def test_validator_rejects_unknown_exemplar_bucket(self):
        bad = ("# HELP h h\n# TYPE h histogram\n"
               'h_bucket{le="0.1"} 1\nh_bucket{le="+Inf"} 1\n'
               "h_sum 0.05\nh_count 1\n"
               '# EXEMPLAR h_bucket{le="9.9"} trace_id=t value=0.05\n')
        assert validate_exposition(bad) != []


# ------------------------------------------------------- trace context

class TestTraceContext:
    def test_mint_is_unique_and_kind_prefixed(self):
        from coreth_tpu.metrics import tracectx

        a, b = tracectx.mint("rpc"), tracectx.mint("rpc")
        assert a != b and a.startswith("rpc-") and b.startswith("rpc-")

    def test_scope_installs_and_restores(self):
        from coreth_tpu.metrics import tracectx

        assert tracectx.current() is None
        ctx = tracectx.begin("insert")
        with tracectx.scope(ctx):
            assert tracectx.current() is ctx
            assert tracectx.current_id() == ctx.trace_id
        assert tracectx.current() is None
        with tracectx.scope(None):  # no-op scope needs no branching
            assert tracectx.current() is None

    def test_ring_is_bounded_and_keyed(self):
        from coreth_tpu.metrics.tracectx import TraceRing

        ring = TraceRing(capacity=3)
        for i in range(5):
            ring.put({"trace_id": f"t-{i}", "outcome": "shed"})
        assert len(ring) == 3
        assert ring.get("t-0") is None  # evicted
        assert ring.get("t-4")["trace_id"] == "t-4"
        assert [r["trace_id"] for r in ring.last(2)] == ["t-3", "t-4"]

    def test_spans_bounded_per_trace(self):
        from coreth_tpu.metrics import tracectx

        ctx = tracectx.begin("rpc")
        for i in range(tracectx.MAX_SPANS_PER_TRACE + 10):
            ctx.add_span({"name": f"s{i}"})
        assert len(ctx.spans) == tracectx.MAX_SPANS_PER_TRACE

    def test_deadline_exceeded_carries_trace_id(self):
        from coreth_tpu.metrics import tracectx
        from coreth_tpu.utils import deadline as dl

        ctx = tracectx.begin("rpc")
        with tracectx.scope(ctx):
            with dl.scope(dl.Deadline(0.0)):
                with pytest.raises(dl.DeadlineExceeded) as e:
                    dl.check()
        assert e.value.trace_id == ctx.trace_id
        assert ctx.trace_id in str(e.value)


# ------------------------------------------------------- healthz draining

class TestHealthzDraining:
    def _vm(self, server):
        import types as _types

        chain = _types.SimpleNamespace(
            acceptor_error=None,
            last_accepted=_types.SimpleNamespace(number=7))
        return _types.SimpleNamespace(blockchain=chain, rpc_server=server)

    def test_health_check_reports_draining(self):
        from coreth_tpu.rpc.server import RPCServer
        from coreth_tpu.vm.api import health_check

        srv = RPCServer()
        vm = self._vm(srv)
        assert health_check(vm)["healthy"] is True
        srv.stop()
        verdict = health_check(vm)
        assert verdict["healthy"] is False
        assert verdict["draining"] is True

    def test_healthz_endpoint_returns_503_while_draining(self):
        from coreth_tpu.rpc.server import RPCServer
        from coreth_tpu.vm.api import health_check

        srv = RPCServer()
        vm = self._vm(srv)
        msrv = MetricsHTTPServer(registry=Registry(),
                                 health_fn=lambda: health_check(vm))
        port = msrv.start(host="127.0.0.1", port=0)
        try:
            status, _, body = _get(port, "/healthz")
            assert status == 200 and json.loads(body)["healthy"] is True
            srv.stop()
            status, _, body = _get(port, "/healthz")
            payload = json.loads(body)
            assert status == 503
            assert payload["draining"] is True
        finally:
            msrv.stop()
