"""ReadView consistency (PR 16, lock-free read serving tier).

Concurrent readers racing accept/reorg/degraded flips must only ever
see fully-published views (no torn head, monotonic sequence), read-only
RPC methods must execute with ZERO chainmu acquisitions (the inverse of
RaceDetector.require_lock: a counting-lock proxy proves the lock is
never entered from reader threads), the view path must answer
bit-identically to the seed resolution path on a differential corpus,
and a mini traffic storm must keep its latency SLO while the chaos
conductor injects storage faults underneath it.
"""

import json
import random
import threading
import time

import pytest

from coreth_tpu import fault, params
from coreth_tpu.consensus.dummy import new_dummy_engine
from coreth_tpu.core.blockchain import BlockChain, CacheConfig
from coreth_tpu.core.chain_makers import generate_chain
from coreth_tpu.core.genesis import Genesis, GenesisAccount
from coreth_tpu.core.txpool import TxPool, TxPoolConfig
from coreth_tpu.core.types import Signer, Transaction
from coreth_tpu.crypto.secp256k1 import priv_to_address
from coreth_tpu.eth.api import EthAPI
from coreth_tpu.eth.backend import EthBackend
from coreth_tpu.ethdb import MemoryDB
from coreth_tpu.ethdb.faultdb import FaultInjectingDB
from coreth_tpu.rpc.server import RPCError, RPCServer
from coreth_tpu.state.database import Database
from coreth_tpu.trie.triedb import TrieDatabase

KEY = b"\x44" * 32
ADDR = priv_to_address(KEY)
DEST = b"\xcc" * 20
SIGNER = Signer(43112)
FUND = 10**21


def make_tx(nonce, value=7):
    t = Transaction(type=2, chain_id=43112, nonce=nonce, max_fee=10**12,
                    max_priority_fee=10**9, gas=21000, to=DEST, value=value)
    return SIGNER.sign(t, KEY)


def build_chain(cache_config=None, diskdb=None):
    diskdb = diskdb if diskdb is not None else MemoryDB()
    genesis = Genesis(
        config=params.TEST_CHAIN_CONFIG, gas_limit=params.CORTINA_GAS_LIMIT,
        alloc={ADDR: GenesisAccount(balance=FUND)},
    )
    chain = BlockChain(
        diskdb, cache_config or CacheConfig(pruning=True, commit_interval=4),
        params.TEST_CHAIN_CONFIG, genesis, new_dummy_engine(),
        state_database=Database(TrieDatabase(diskdb)),
    )
    return chain


def make_blocks(chain, n, value=7, parent=None):
    nonce = chain.state().get_nonce(ADDR)
    blocks, _ = generate_chain(
        chain.config, parent or chain.current_block, chain.engine,
        chain.state_database, n,
        gen=lambda i, bg: bg.add_tx(make_tx(nonce + i, value)),
    )
    return blocks


# ---------------------------------------------------------- publication

def test_view_published_at_boot_and_tracks_heads():
    chain = build_chain()
    try:
        v0 = chain.read_view()
        assert v0 is not None
        assert v0.accepted.hash() == chain.genesis_block.hash()
        assert v0.preferred.hash() == chain.genesis_block.hash()
        assert not v0.degraded

        blocks = make_blocks(chain, 3)
        chain.insert_block(blocks[0])
        v1 = chain.read_view()
        assert v1.seq > v0.seq
        assert v1.preferred.hash() == blocks[0].hash()
        assert v1.accepted.hash() == chain.genesis_block.hash()

        chain.accept(blocks[0])
        chain.drain_acceptor_queue()
        v2 = chain.read_view()
        assert v2.seq > v1.seq
        assert v2.accepted.hash() == blocks[0].hash()
    finally:
        chain.stop()


def test_view_flips_on_reorg():
    chain = build_chain()
    try:
        fork_a = make_blocks(chain, 1, value=7)
        fork_b = make_blocks(chain, 1, value=9)
        chain.insert_block(fork_a[0])
        assert chain.read_view().preferred.hash() == fork_a[0].hash()
        # sibling of the preferred tip: registered but not canonical
        chain.insert_block(fork_b[0])
        seq_before = chain.read_view().seq
        chain.set_preference(fork_b[0])
        v = chain.read_view()
        assert v.preferred.hash() == fork_b[0].hash()
        assert v.seq > seq_before
    finally:
        chain.stop()


def test_view_reflects_degraded_flips():
    chain = build_chain(CacheConfig(pruning=True, commit_interval=4096,
                                    db_retry_budget=1),
                        diskdb=FaultInjectingDB(MemoryDB()))
    try:
        blocks = make_blocks(chain, 3)
        chain.insert_block(blocks[0])
        chain.join_tail()
        chain.accept(blocks[0])
        chain.drain_acceptor_queue()
        assert not chain.read_view().degraded

        fault.set_failpoint("ethdb/before_put", "raise*64")
        chain.insert_block(blocks[1])
        try:
            chain.join_tail()
        except Exception:  # noqa: BLE001 - the tear may surface here
            pass
        for _ in range(500):  # the flip lands from the tail worker
            if chain.read_view().degraded:
                break
            time.sleep(0.01)
        v = chain.read_view()
        assert v.degraded, "view never published the degraded flip"
        # heads survive the flip intact — no torn view
        assert v.accepted.hash() == blocks[0].hash()

        fault.clear_all()
        chain.insert_block(blocks[2])  # probe + replay + re-promote
        chain.join_tail()
        assert not chain.read_view().degraded
    finally:
        fault.clear_all()
        chain.stop()


# ------------------------------------------------- concurrent coherence

def test_concurrent_readers_see_only_fully_published_views():
    """Seeded multithreaded drill: while inserts/accepts advance the
    chain, every view a reader grabs must be internally coherent
    (accepted never ahead of preferred on a linear chain) and the
    stream of views per reader must be monotonic in seq and accepted
    height — a torn publication would break one of these."""
    from coreth_tpu.utils.racecheck import LockOrderWitness

    chain = build_chain()
    # runtime lock-order witness (SA013's runtime twin): the insert/
    # accept writer nests these locks under the readers' noses; any
    # acquisition inverting the canonical order is a violation
    witness = LockOrderWitness()
    witness.wrap(chain, "chainmu", "BlockChain.chainmu")
    witness.wrap(chain, "_acceptor_tip_lock", "BlockChain._acceptor_tip_lock")
    witness.wrap(chain, "_insert_recs_mu", "BlockChain._insert_recs_mu")
    witness.wrap(chain, "_view_mu", "BlockChain._view_mu")
    blocks = make_blocks(chain, 24)
    stop = threading.Event()
    errors = []

    def reader(seed):
        rng = random.Random(seed)
        last_seq = 0
        last_accepted = 0
        while not stop.is_set():
            try:
                v = chain.read_view()
                if v.seq < last_seq:
                    errors.append(f"seq regressed {last_seq} -> {v.seq}")
                if v.accepted.number < last_accepted:
                    errors.append(
                        f"accepted regressed {last_accepted} -> "
                        f"{v.accepted.number}")
                if v.accepted.number > v.preferred.number:
                    errors.append(
                        f"torn head: accepted {v.accepted.number} > "
                        f"preferred {v.preferred.number}")
                last_seq, last_accepted = v.seq, v.accepted.number
                if rng.random() < 0.3:
                    st = chain.state_at_view(v, v.accepted.root)
                    if st.get_balance(ADDR) <= 0:
                        errors.append("funded account read as empty")
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

    readers = [threading.Thread(target=reader, args=(i,)) for i in range(4)]
    for t in readers:
        t.start()
    try:
        for b in blocks:
            chain.insert_block(b)
            chain.accept(b)
        chain.drain_acceptor_queue()
    finally:
        stop.set()
        for t in readers:
            t.join()
    assert not errors, errors[:5]
    assert witness.violations == [], witness.violations[:5]
    # the writer really did nest locks while we watched
    assert ("BlockChain.chainmu", "BlockChain._view_mu") in witness.edges
    witness.unwrap_all()
    chain.stop()


# -------------------------------------------------- chainmu-free reads

class CountingLock:
    """RLock proxy recording per-thread acquisition counts — the
    inverse of RaceDetector.require_lock: proves a code path NEVER
    enters the lock."""

    def __init__(self, inner):
        self._inner = inner
        self._mu = threading.Lock()
        self.acquisitions = {}

    def _count(self):
        ident = threading.get_ident()
        with self._mu:
            self.acquisitions[ident] = self.acquisitions.get(ident, 0) + 1

    def acquire(self, *a, **kw):
        self._count()
        return self._inner.acquire(*a, **kw)

    def release(self):
        return self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def test_read_methods_never_acquire_chainmu():
    """Racecheck ownership test (ISSUE 16 acceptance): the read-only
    RPC surface — blockNumber, getBalance, getTransactionCount,
    getStorageAt, call, getLogs, gasPrice — executes with zero chainmu
    acquisitions even while a writer inserts/accepts concurrently."""
    chain = build_chain()
    counting = CountingLock(chain.chainmu)
    chain.chainmu = counting
    backend = EthBackend(
        chain, TxPool(TxPoolConfig(), params.TEST_CHAIN_CONFIG, chain))
    api = EthAPI(backend)
    blocks = make_blocks(chain, 16)
    chain.insert_block(blocks[0])
    chain.accept(blocks[0])
    chain.drain_acceptor_queue()

    stop = threading.Event()
    reader_idents = []
    errors = []
    dest = "0x" + DEST.hex()
    addr = "0x" + ADDR.hex()

    def reader():
        reader_idents.append(threading.get_ident())
        while not stop.is_set():
            try:
                api.blockNumber()
                api.getBalance(dest, "latest")
                api.getTransactionCount(addr, "latest")
                api.getStorageAt(dest, "0x0", "latest")
                api.call({"to": dest}, "latest")
                api.getLogs({"fromBlock": "0x0", "toBlock": "latest"})
                api.gasPrice()
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

    readers = [threading.Thread(target=reader) for _ in range(3)]
    for t in readers:
        t.start()
    try:
        for b in blocks[1:]:
            chain.insert_block(b)
            chain.accept(b)
        chain.drain_acceptor_queue()
        time.sleep(0.05)  # let readers spin against the settled tip too
    finally:
        stop.set()
        for t in readers:
            t.join()
    assert not errors, errors[:5]
    writer_acquisitions = sum(
        n for ident, n in counting.acquisitions.items()
        if ident not in reader_idents)
    assert writer_acquisitions > 0, "harness vacuous: writer never locked"
    for ident in reader_idents:
        assert counting.acquisitions.get(ident, 0) == 0, (
            f"reader thread acquired chainmu "
            f"{counting.acquisitions[ident]} time(s)")
    chain.stop()


# ---------------------------------------------------- differential corpus

class SeedBackend(EthBackend):
    """The pre-ReadView resolution path, verbatim (chain pointers +
    chain-global state_at), as the differential oracle."""

    def last_accepted_block(self):
        return self.chain.last_accepted_block()

    def current_block(self):
        return self.chain.current_block

    def _block_in_view(self, view, tag):
        return self.block_by_tag(tag)

    def block_by_tag(self, tag):
        if tag in ("latest", "accepted"):
            return self.last_accepted_block()
        if tag == "pending":
            return self.current_block()
        if tag == "earliest":
            return self.chain.genesis_block
        from coreth_tpu.eth.api import parse_hex

        number = parse_hex(tag)
        head = self.last_accepted_block().number
        if number > head and not self.allow_unfinalized_queries:
            raise RPCError(
                -32000,
                f"cannot query unfinalized data (requested {number} > "
                f"accepted {head})")
        return self.chain.get_block_by_number(number)

    def state_at_tag(self, tag):
        blk = self.block_by_tag(tag)
        if blk is None:
            raise RPCError(-32000, "block not found")
        return self.chain.state_at(blk.root)

    def state_at_root(self, root):
        return self.chain.state_at(root)

    def do_call(self, call_obj, tag, wrap_state=None):
        from coreth_tpu.core.state_processor import new_block_context
        from coreth_tpu.core.state_transition import GasPool, apply_message
        from coreth_tpu.evm.evm import EVM, Config, TxContext

        blk = self.block_by_tag(tag)
        if blk is None:
            raise RPCError(-32000, "block not found")
        state = self.chain.state_at(blk.root)
        if wrap_state is not None:
            state = wrap_state(state)
        msg = self._call_msg(call_obj, blk.gas_limit)
        evm = EVM(
            new_block_context(blk.header, self.chain),
            TxContext(origin=msg.from_, gas_price=msg.gas_price),
            state, self.chain_config, Config(no_base_fee=True),
        )
        return apply_message(evm, msg, GasPool(2**63)), msg, blk


def test_view_path_bit_identical_to_seed_path():
    """Every read method must answer byte-for-byte what the seed
    resolution path answers on a settled chain."""
    chain = build_chain()
    try:
        blocks = make_blocks(chain, 6)
        for b in blocks:
            chain.insert_block(b)
            chain.accept(b)
        chain.drain_acceptor_queue()
        chain.join_tail()

        pool = TxPool(TxPoolConfig(), params.TEST_CHAIN_CONFIG, chain)
        seed_server, view_server = RPCServer(), RPCServer()
        seed_server.register_api("eth", EthAPI(SeedBackend(chain, pool)))
        view_server.register_api("eth", EthAPI(EthBackend(chain, pool)))

        dest = "0x" + DEST.hex()
        addr = "0x" + ADDR.hex()
        tx0 = "0x" + blocks[0].transactions[0].hash().hex()
        corpus = [
            ("eth_blockNumber", []),
            ("eth_chainId", []),
            ("eth_getBalance", [dest, "latest"]),
            ("eth_getBalance", [dest, "pending"]),
            ("eth_getBalance", [dest, "earliest"]),
            ("eth_getBalance", [addr, "0x3"]),
            ("eth_getTransactionCount", [addr, "latest"]),
            ("eth_getStorageAt", [dest, "0x0", "latest"]),
            ("eth_getCode", [dest, "latest"]),
            ("eth_call", [{"to": dest}, "latest"]),
            ("eth_call", [{"from": addr, "to": dest, "value": "0x1"},
                          "pending"]),
            ("eth_estimateGas", [{"from": addr, "to": dest,
                                  "value": "0x1"}]),
            ("eth_gasPrice", []),
            ("eth_maxPriorityFeePerGas", []),
            ("eth_feeHistory", ["0x4", "latest", [25, 75]]),
            ("eth_getLogs", [{"fromBlock": "0x0", "toBlock": "latest"}]),
            ("eth_getBlockByNumber", ["latest", True]),
            ("eth_getBlockByNumber", ["0x2", False]),
            ("eth_getTransactionByHash", [tx0]),
            ("eth_getTransactionReceipt", [tx0]),
            ("eth_getHeaderByNumber", ["0x1"]),
        ]
        for method, prm in corpus:
            req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                              "params": prm}).encode()
            seed_raw = seed_server.handle_raw(req)
            view_raw = view_server.handle_raw(req)
            assert seed_raw == view_raw, (
                f"{method}{prm} diverged:\nseed {seed_raw!r}\n"
                f"view {view_raw!r}")
    finally:
        chain.stop()


# ------------------------------------------- storm under chaos conductor

@pytest.mark.slow
def test_mini_storm_keeps_slo_under_chaos_conductor():
    """Reads keep their latency SLO while the seeded chaos conductor
    injects storage/device faults into the same chain underneath them:
    every request completes (result OR typed error — no hangs) and the
    p99 stays far below the conductor's step budget, because the read
    path never queues on chainmu behind a faulted write."""
    from coreth_tpu.fault.chaos import Conductor

    cond = Conductor(seed=3, steps=8, kill_drill=False)
    stop = threading.Event()
    latencies = []
    bad = []
    lat_mu = threading.Lock()

    orig_shutdown = cond._shutdown

    def shutdown_after_readers():
        stop.set()
        for t in readers:
            t.join(timeout=10)
        orig_shutdown()

    cond._shutdown = shutdown_after_readers

    def reader(seed):
        rng = random.Random(seed)
        while not stop.is_set():
            addr = "0x" + (cond.addr1 if rng.random() < 0.5
                           else cond.addr2).hex()
            method, prm = rng.choice([
                ("eth_blockNumber", []),
                ("eth_getBalance", [addr, "latest"]),
                ("eth_gasPrice", []),
                ("eth_getTransactionCount", [addr, "latest"]),
            ])
            req = json.dumps({"jsonrpc": "2.0", "id": 7, "method": method,
                              "params": prm}).encode()
            t0 = time.monotonic()
            try:
                resp = json.loads(cond.server.handle_raw(req))
                if "result" not in resp and "error" not in resp:
                    bad.append(resp)
            except Exception as e:  # noqa: BLE001
                bad.append(repr(e))
            with lat_mu:
                latencies.append(time.monotonic() - t0)

    run_err = []

    def run_conductor():
        try:
            cond.result = cond.run()
        except Exception as e:  # noqa: BLE001
            run_err.append(repr(e))
            stop.set()

    runner = threading.Thread(target=run_conductor)
    runner.start()
    # the conductor boots its chain + server inside run()
    for _ in range(1000):
        if hasattr(cond, "server") or run_err:
            break
        time.sleep(0.01)
    assert hasattr(cond, "server"), run_err
    readers = [threading.Thread(target=reader, args=(i,)) for i in range(4)]
    for t in readers:
        t.start()
    runner.join(timeout=300)
    stop.set()
    for t in readers:
        t.join(timeout=10)
    assert not run_err, run_err
    assert not bad, bad[:5]
    # the conductor's per-step lock-order invariant (#6) covered this
    # storm: the witness saw real nesting and recorded no inversions
    assert not [v for v in cond.result["violations"]
                if v["what"] == "lock-order"], cond.result["violations"]
    assert cond.witness.edges, "lock-order witness saw no lock traffic"
    assert latencies, "storm produced no samples"
    latencies.sort()
    p99 = latencies[min(len(latencies) - 1, int(len(latencies) * 0.99))]
    assert p99 < 5.0, f"read p99 {p99:.3f}s blew the SLO under chaos"
