"""VM plumbing: fork-scheduled gas-price floors, static genesis service,
ext-data-hash repair tables, factory (plugin/evm/{gasprice_update,
static_service,ext_data_hashes,factory}.go)."""

import json
import time

from coreth_tpu import params
from coreth_tpu.vm.plumbing import (
    GasPriceUpdater,
    StaticService,
    factory_new,
    load_ext_data_hashes,
    repaired_ext_data_hash,
)


class FakePool:
    def __init__(self):
        self.price = None
        self.min_fee = None

    def set_price_floor(self, p):
        self.price = p

    def set_min_fee_floor(self, f):
        self.min_fee = f


def test_gas_price_updater_past_forks_apply_immediately():
    pool = FakePool()
    cfg = params.TEST_CHAIN_CONFIG  # all forks active at t=0
    gpu = GasPriceUpdater(pool, cfg, clock=lambda: 10**9)
    gpu.start()
    # AP3 zeroes the gas price floor; AP4 sets the final min fee
    assert pool.price == 0
    assert pool.min_fee == params.APRICOT_PHASE4_MIN_BASE_FEE
    gpu.stop()


def test_gas_price_updater_future_fork_scheduled():
    import dataclasses

    pool = FakePool()
    now = time.time()
    cfg = dataclasses.replace(
        params.TEST_CHAIN_CONFIG,
        apricot_phase1_time=int(now) + 3600,
        apricot_phase3_time=None, apricot_phase4_time=None)
    gpu = GasPriceUpdater(pool, cfg)
    gpu.start()
    # launch floor applied now; AP1 waits on a timer
    assert pool.price == params.LAUNCH_MIN_GAS_PRICE
    assert len(gpu._timers) == 1
    gpu.stop()
    assert not gpu._timers


def test_static_service_build_genesis_roundtrip():
    svc = StaticService()
    spec = {"config": {"chainId": 43112}, "alloc": {}}
    out = svc.buildGenesis(spec)
    assert out["encoding"] == "hex"
    assert json.loads(bytes.fromhex(out["bytes"][2:])) == spec


def test_ext_data_hash_repair_table():
    h = "0x" + "ab" * 32
    repaired = "0x" + "cd" * 32
    load_ext_data_hashes(5, json.dumps({h: repaired}).encode())
    assert repaired_ext_data_hash(5, bytes.fromhex("ab" * 32)) == \
        bytes.fromhex("cd" * 32)
    assert repaired_ext_data_hash(5, b"\x00" * 32) is None
    assert repaired_ext_data_hash(1, bytes.fromhex("ab" * 32)) is None


def test_factory_new_returns_uninitialized_vm():
    vm = factory_new()
    assert vm.initialized is False
