#!/bin/bash
# Probe the axon TPU tunnel every 8 minutes; log liveness. On success:
#   1. if BENCH_EARLY_r05.json is missing, land the early bench first
#      (small leg + micro link/dispatch/kernel decomposition — the
#      round's minimum hardware evidence, VERDICT r4 #1+#2);
#   2. if BENCH_FULL_r05.json is missing, run the FULL bench (big +
#      resident + incremental legs) and land it.
# Failed/partial attempts are preserved under tools/ so even a wedge
# mid-leg leaves its decomposition data for PERF.md (r04 lost a whole
# ALIVE window this way).
# tools/BENCH_RUNNING exists while a bench is in flight so other jobs on
# this 1-core container can avoid starving the device watchdogs.
#
# Probe discipline per memory/axon-tunnel-operations: PYTHONPATH must
# include /root/.axon_site; generous timeout (120s >> healthy first-op
# ~1.6-40s) so we never kill a merely-slow device-attached process.
cd /root/repo
LOG=tools/tunnel_probe.log
ROUND=r05
while true; do
  ts=$(date -u +%H:%M:%S)
  if timeout 120 env PYTHONPATH=/root/repo:/root/.axon_site python -c "
import jax, jax.numpy as jnp
(jnp.zeros(8)+1).block_until_ready()
" >/dev/null 2>&1; then
    echo "$ts ALIVE" >> "$LOG"
    if [ ! -f BENCH_EARLY_${ROUND}.json ]; then
      echo "$ts running early bench" >> "$LOG"
      touch tools/BENCH_RUNNING
      timeout 900 env PYTHONPATH=/root/repo:/root/.axon_site \
        python bench.py --early > /tmp/bench_early_probe.json 2>> "$LOG"
      rc=$?
      # land only a clean early report (device number present, no
      # watchdog error) — a partial must NOT suppress the retry
      if [ $rc -eq 0 ] && grep -q '"scope": "small"' /tmp/bench_early_probe.json \
         && ! grep -q '"error":' /tmp/bench_early_probe.json; then
        cp /tmp/bench_early_probe.json BENCH_EARLY_${ROUND}.json
        echo "$ts early bench done" >> "$LOG"
      else
        cp /tmp/bench_early_probe.json "tools/bench_early_partial_${ts//:/}.json" 2>/dev/null
        echo "$ts early bench partial/failed (rc=$rc; partial saved)" >> "$LOG"
      fi
      rm -f tools/BENCH_RUNNING
    elif [ ! -f BENCH_FULL_${ROUND}.json ]; then
      echo "$ts running FULL bench" >> "$LOG"
      touch tools/BENCH_RUNNING
      timeout 1800 env PYTHONPATH=/root/repo:/root/.axon_site \
        python bench.py > /tmp/bench_full_probe.json 2>> "$LOG"
      rc=$?
      # land it only if a device leg actually ran (scope big/resident/
      # incremental); a wedge partial with scope=small is NOT the full
      # artifact and should retry next ALIVE window
      if [ $rc -eq 0 ] \
         && grep -q '"scope": "\(big\|resident\|incremental\)' /tmp/bench_full_probe.json \
         && ! grep -q '"res_error"\|"inc_error"\|"error":' /tmp/bench_full_probe.json; then
        cp /tmp/bench_full_probe.json BENCH_FULL_${ROUND}.json
        echo "$ts FULL bench done" >> "$LOG"
      else
        cp /tmp/bench_full_probe.json "tools/bench_full_partial_${ts//:/}.json" 2>/dev/null
        echo "$ts FULL bench partial/failed (rc=$rc; partial saved)" >> "$LOG"
      fi
      rm -f tools/BENCH_RUNNING
    fi
  else
    echo "$ts wedged (probe timeout/err)" >> "$LOG"
  fi
  sleep 480
done
