#!/bin/bash
# Probe the axon TPU tunnel every 8 minutes; on first success, run the
# early-bench (bench.py quick leg incl. Pallas parity) and write
# BENCH_EARLY_r04.json. Appends one status line per probe to
# tools/tunnel_probe.log so the round has a liveness record either way.
#
# Probe discipline per memory/axon-tunnel-operations: PYTHONPATH must
# include /root/.axon_site; generous timeout (120s >> healthy first-op
# ~1.6-40s) so we never kill a merely-slow device-attached process.
cd /root/repo
LOG=tools/tunnel_probe.log
while true; do
  ts=$(date -u +%H:%M:%S)
  if timeout 120 env PYTHONPATH=/root/repo:/root/.axon_site python -c "
import jax, jax.numpy as jnp
(jnp.zeros(8)+1).block_until_ready()
" >/dev/null 2>&1; then
    echo "$ts ALIVE" >> "$LOG"
    if [ ! -f BENCH_EARLY_r04.json ]; then
      echo "$ts running early bench" >> "$LOG"
      timeout 900 env PYTHONPATH=/root/repo:/root/.axon_site \
        CORETH_TPU_BENCH_EARLY=1 python bench.py --early \
        > BENCH_EARLY_r04.json 2>> "$LOG" \
        && echo "$ts early bench done" >> "$LOG" \
        || echo "$ts early bench FAILED" >> "$LOG"
    fi
  else
    echo "$ts wedged (probe timeout/err)" >> "$LOG"
  fi
  sleep 480
done
