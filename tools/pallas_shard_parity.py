"""One-shot numeric parity of the Pallas kernel under shard_map on the
virtual CPU mesh (VERDICT r4 #4's interpret-mode leg).

Interpret-mode Pallas costs >10 minutes of XLA-CPU compile per program on
a single-core box, so this runs OUT of the dryrun/CI budget and records
its result as MULTICHIP_PALLAS_r{N}.json. The words come from a REAL
planner segment (8192 one-block leaf rows), each device hashing a
1024-lane shard through the VMEM-kernel's interpreter path; digests must
match the XLA scan kernel bit-for-bit.

Usage:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python tools/pallas_shard_parity.py [out.json]
"""

import json
import os
import random
import sys
import time

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert len(jax.devices()) >= 8, (
    f"need 8 virtual devices, have {len(jax.devices())} — the recorded "
    "artifact must reflect a genuinely sharded run")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from coreth_tpu.native.mpt import plan_from_items  # noqa: E402
from coreth_tpu.ops.keccak_pallas import staged_seg_impl  # noqa: E402
from coreth_tpu.ops.keccak_staged import _segment_keccak  # noqa: E402
from coreth_tpu.parallel import make_mesh, sharded_seg_impl  # noqa: E402


def main():
    n_devices = 8
    mesh = make_mesh(n_devices)
    rng = random.Random(9)
    items = [(rng.randbytes(32), rng.randbytes(rng.randint(40, 90)))
             for _ in range(7000)]
    plan = plan_from_items(items)
    specs, flat_words, *_ = plan.export_words()
    seg = next(s for s in specs if s.blocks == 1 and s.lanes >= 8192)
    off = 0
    for s in specs:
        if s is seg:
            break
        off += s.lanes * s.blocks * 34
    lanes = n_devices * 1024
    words = np.ascontiguousarray(
        flat_words[off:off + lanes * 34]).reshape(lanes, 1, 34)

    sharded = sharded_seg_impl(mesh, seg_impl=staged_seg_impl(interpret=True))
    t0 = time.time()
    dig_p = np.asarray(sharded(words))
    t_pallas = time.time() - t0
    dig_x = np.asarray(_segment_keccak(words))
    ok = bool((dig_p == dig_x).all())
    out = {
        "check": "pallas_kernel_under_shard_map_interpret",
        "devices": n_devices,
        "lanes_per_shard": lanes // n_devices,
        "lanes_total": lanes,
        "source": "real planner segment (7000-leaf trie, 1-block leaf rows)",
        "parity_vs_xla": ok,
        "wall_s": round(t_pallas, 1),
    }
    path = sys.argv[1] if len(sys.argv) > 1 else "MULTICHIP_PALLAS_r04.json"
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    assert ok, "sharded Pallas digests differ from the XLA kernel"


if __name__ == "__main__":
    main()
