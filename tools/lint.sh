#!/usr/bin/env bash
# One-shot lint runner: repo-native static analysis (always) + mypy over
# the strict core subset (only when mypy is installed — the CI image may
# not ship it).  Exits non-zero if any enabled stage fails.
set -u
cd "$(dirname "$0")/.."

rc=0

echo "== coreth_tpu.analysis (AST lint + interprocedural: SA001-SA014) =="
# --strict-baseline: stale allowlist entries fail too, so a fixed
# finding can't leave a masking entry behind; the run includes the
# whole-program passes (call graph, lock-order lint, promotions)
python -m coreth_tpu.analysis --strict-baseline || rc=1

echo
echo "== coreth_tpu.core.exec_shards --smoke (fork/kill/respawn shard pool) =="
# jax-less by design (the module imports no EVM machinery at module
# scope): forks 2 workers, SIGKILLs one, asserts the respawn ladder
python -m coreth_tpu.core.exec_shards --smoke || rc=1

echo
echo "== coreth_tpu.metrics --check (Prometheus exposition self-test) =="
python -m coreth_tpu.metrics --check || rc=1

echo
echo "== coreth_tpu.bench.trajectory --check (bench regression sentinel) =="
# skips cleanly (exit 0) when the checkout carries no BENCH_* artifacts
python -m coreth_tpu.bench.trajectory --check || rc=1

echo
echo "== coreth_tpu.fault.chaos (deterministic chaos smoke, seed 1) =="
# skips cleanly (exit 0) when jax is unavailable in the lint image;
# any invariant violation in the 50-step conductor run fails the lint —
# including #6, the runtime lock-order witness (SA013's runtime twin)
if python -c "import jax" >/dev/null 2>&1; then
    JAX_PLATFORMS=cpu python -m coreth_tpu.fault.chaos --steps 50 --seed 1 \
        || rc=1
else
    echo "chaos smoke: jax not installed; skipping"
fi

echo
echo "== benches/bench_storm.py --smoke (~2s open-loop read-storm smoke) =="
# liveness probe for the lock-free read tier + PR-7 overload stack, not
# a measurement (smoke artifacts are excluded from the trajectory);
# skips cleanly when jax is unavailable in the lint image
if python -c "import jax" >/dev/null 2>&1; then
    JAX_PLATFORMS=cpu python benches/bench_storm.py --smoke || rc=1
else
    echo "storm smoke: jax not installed; skipping"
fi

echo
if python -c "import mypy" >/dev/null 2>&1; then
    echo "== mypy (strict core subset, mypy.ini) =="
    python -m mypy --config-file mypy.ini || rc=1
else
    echo "== mypy: not installed; skipping (config checked in at mypy.ini) =="
fi

exit $rc
